"""x/gov: proposal submission, power-weighted voting, tally, execution.

The reference routes parameter changes through the SDK gov module whose
proposal handler is wrapped by x/paramfilter's blocklist
(x/paramfilter/gov_handler.go:36-60: a ParamChangeProposal touching any
hardfork-only param fails WITHOUT partial application).  This module
implements that flow natively: MsgSubmitProposal (deposit + param changes)
-> voting window measured in blocks -> EndBlocker tally against bonded
power (quorum 1/3, threshold 1/2, veto 1/3 of non-abstain) -> gated
execution applying all changes atomically.

Gov params live in the params store (VotingPeriodBlocks, MinDeposit,
QuorumPpm, ThresholdPpm, VetoPpm) — themselves gov-changeable, except where
the blocklist says otherwise.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.state.tx import MsgSubmitProposal, MsgVote

PROPOSAL_STATUS_VOTING = 1
PROPOSAL_STATUS_PASSED = 2
PROPOSAL_STATUS_REJECTED = 3
PROPOSAL_STATUS_FAILED = 4  # passed the vote but execution was refused

DEFAULT_VOTING_PERIOD_BLOCKS = 10
DEFAULT_MIN_DEPOSIT = 1_000_000  # 1 TIA in utia
DEFAULT_QUORUM_PPM = 334_000  # 33.4%
DEFAULT_THRESHOLD_PPM = 500_000  # 50%
DEFAULT_VETO_PPM = 334_000  # 33.4%

_PROPOSAL_PREFIX = b"proposal/"
_VOTE_PREFIX = b"vote/"
_NEXT_ID_KEY = b"next_proposal_id"

# The gov module account: escrows deposits AND is the only authority allowed
# to execute MsgParamChange.  It is a module address with no private key, so
# no user transaction can ever carry a valid signature for it — param writes
# happen exclusively through a passed proposal's execution.
GOV_MODULE_ADDR = b"gov-escrow-pool-addr"
assert len(GOV_MODULE_ADDR) == 20


@dataclass
class Proposal:
    id: int
    proposer: bytes
    title: str
    description: str
    changes: Tuple[Tuple[str, str, bytes], ...]
    deposit: int
    submit_height: int
    voting_end_height: int
    status: int = PROPOSAL_STATUS_VOTING
    result_log: str = ""
    # community-pool spend content (distribution CommunityPoolSpendProposal)
    spend_to: bytes = b""
    spend_amount: int = 0

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "id": self.id,
                "proposer": self.proposer.hex(),
                "title": self.title,
                "description": self.description,
                "changes": [
                    [s, k, v.hex()] for s, k, v in self.changes
                ],
                "deposit": self.deposit,
                "submit_height": self.submit_height,
                "voting_end_height": self.voting_end_height,
                "status": self.status,
                "result_log": self.result_log,
                "spend_to": self.spend_to.hex(),
                "spend_amount": self.spend_amount,
            }
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Proposal":
        d = json.loads(raw)
        return cls(
            id=d["id"],
            proposer=bytes.fromhex(d["proposer"]),
            title=d["title"],
            description=d["description"],
            changes=tuple(
                (s, k, bytes.fromhex(v)) for s, k, v in d["changes"]
            ),
            deposit=d["deposit"],
            submit_height=d["submit_height"],
            voting_end_height=d["voting_end_height"],
            status=d["status"],
            result_log=d.get("result_log", ""),
            spend_to=bytes.fromhex(d.get("spend_to", "")),
            spend_amount=d.get("spend_amount", 0),
        )


class GovKeeper:
    """Proposal lifecycle over the gov KV store."""

    def __init__(self, store, bank, staking, params, param_block_list):
        self.store = store
        self.bank = bank
        self.staking = staking
        self.params = params
        self.block_list = param_block_list

    # -- config --------------------------------------------------------

    def voting_period(self) -> int:
        return int(
            self.params.get(
                "gov", "VotingPeriodBlocks", DEFAULT_VOTING_PERIOD_BLOCKS
            )
        )

    def min_deposit(self) -> int:
        return int(self.params.get("gov", "MinDeposit", DEFAULT_MIN_DEPOSIT))

    # -- submission / voting -------------------------------------------

    def submit_proposal(self, msg: MsgSubmitProposal, height: int) -> int:
        spend_amount = getattr(msg, "spend_amount", 0)
        if not msg.changes and not spend_amount:
            raise ValueError("proposal carries no content")
        if spend_amount and len(getattr(msg, "spend_to", b"")) != 20:
            raise ValueError("community-pool spend needs a 20-byte recipient")
        if msg.deposit < self.min_deposit():
            raise ValueError(
                f"deposit {msg.deposit} below minimum {self.min_deposit()}"
            )
        # early blocklist check: a proposal that can never execute is
        # rejected at submission, mirroring the handler-gate intent
        for subspace, key, _ in msg.changes:
            self.block_list.validate_change(subspace, key)
        # deposit escrows into the gov pool (burned on veto, else refunded)
        self.bank.send(msg.proposer, GOV_MODULE_ADDR, msg.deposit)
        pid = self._next_id()
        prop = Proposal(
            id=pid,
            proposer=msg.proposer,
            title=msg.title,
            description=msg.description,
            changes=msg.changes,
            deposit=msg.deposit,
            submit_height=height,
            voting_end_height=height + self.voting_period(),
            spend_to=getattr(msg, "spend_to", b""),
            spend_amount=spend_amount,
        )
        self._put(prop)
        return pid

    def vote(self, msg: MsgVote, height: int) -> None:
        prop = self.proposal(msg.proposal_id)
        if prop is None:
            raise ValueError(f"no proposal {msg.proposal_id}")
        if prop.status != PROPOSAL_STATUS_VOTING:
            raise ValueError(f"proposal {prop.id} is not in voting")
        if height > prop.voting_end_height:
            raise ValueError(f"voting on proposal {prop.id} has ended")
        if msg.option not in (1, 2, 3, 4):
            raise ValueError(f"invalid vote option {msg.option}")
        power = self.staking.powers_snapshot().get(msg.voter, 0)
        if power <= 0:
            raise ValueError("only bonded validators vote in this gov model")
        self.store.set(
            _VOTE_PREFIX + msg.proposal_id.to_bytes(8, "big") + msg.voter,
            bytes([msg.option]),
        )

    # -- tally / execution ---------------------------------------------

    def end_blocker(self, height: int, app) -> List[dict]:
        """Tally every proposal whose voting window closed this block."""
        events = []
        for prop in self.proposals():
            if prop.status != PROPOSAL_STATUS_VOTING:
                continue
            if height < prop.voting_end_height:
                continue
            events.append(self._tally_and_execute(prop, app))
        return events

    def _tally_and_execute(self, prop: Proposal, app) -> dict:
        powers = self.staking.powers_snapshot()
        total_power = sum(powers.values())
        yes = no = abstain = veto = 0
        prefix = _VOTE_PREFIX + prop.id.to_bytes(8, "big")
        for key, val in self.store.iterate(prefix):
            voter = key[len(prefix):]
            power = powers.get(voter, 0)
            opt = val[0]
            if opt == MsgVote.OPTION_YES:
                yes += power
            elif opt == MsgVote.OPTION_NO:
                no += power
            elif opt == MsgVote.OPTION_ABSTAIN:
                abstain += power
            elif opt == MsgVote.OPTION_VETO:
                veto += power
        turnout = yes + no + abstain + veto
        non_abstain = yes + no + veto
        quorum_ppm = int(self.params.get("gov", "QuorumPpm", DEFAULT_QUORUM_PPM))
        threshold_ppm = int(
            self.params.get("gov", "ThresholdPpm", DEFAULT_THRESHOLD_PPM)
        )
        veto_ppm = int(self.params.get("gov", "VetoPpm", DEFAULT_VETO_PPM))
        burn_deposit = False
        if total_power == 0 or turnout * 1_000_000 < total_power * quorum_ppm:
            prop.status = PROPOSAL_STATUS_REJECTED
            prop.result_log = "quorum not reached"
        elif non_abstain > 0 and veto * 1_000_000 > non_abstain * veto_ppm:
            prop.status = PROPOSAL_STATUS_REJECTED
            prop.result_log = "vetoed"
            burn_deposit = True
        elif non_abstain > 0 and yes * 1_000_000 > non_abstain * threshold_ppm:
            # execute through the blocklist-gated handler: all-or-nothing
            try:
                self._execute(prop, app)
                prop.status = PROPOSAL_STATUS_PASSED
                prop.result_log = "executed"
            except ValueError as e:
                prop.status = PROPOSAL_STATUS_FAILED
                prop.result_log = f"execution refused: {e}"
        else:
            prop.status = PROPOSAL_STATUS_REJECTED
            prop.result_log = "threshold not reached"
        if burn_deposit:
            self.bank.burn(GOV_MODULE_ADDR, prop.deposit)
        else:
            self.bank.send(GOV_MODULE_ADDR, prop.proposer, prop.deposit)
        self._put(prop)
        return {
            "type": "proposal_tally",
            "proposal_id": prop.id,
            "status": prop.status,
            "log": prop.result_log,
            "yes": yes,
            "no": no,
            "abstain": abstain,
            "veto": veto,
        }

    def _execute(self, prop: Proposal, app) -> None:
        """GovHandler parity (gov_handler.go:36-60): validate EVERY change
        against the blocklist before applying ANY; a community-pool spend
        that cannot be covered refuses the whole proposal."""
        for subspace, key, _ in prop.changes:
            self.block_list.validate_change(subspace, key)
        if prop.spend_amount:
            pool = app.distribution.community_pool()
            if prop.spend_amount > pool:
                raise ValueError(
                    f"community pool {pool}utia cannot cover spend "
                    f"{prop.spend_amount}utia"
                )
        for subspace, key, value in prop.changes:
            app.params.set(subspace, key, json.loads(value))
        if prop.spend_amount:
            app.distribution.spend_community_pool(
                prop.spend_to, prop.spend_amount
            )

    # -- storage -------------------------------------------------------

    def _next_id(self) -> int:
        raw = self.store.get(_NEXT_ID_KEY)
        nid = int.from_bytes(raw, "big") if raw else 1
        self.store.set(_NEXT_ID_KEY, (nid + 1).to_bytes(8, "big"))
        return nid

    def _put(self, prop: Proposal) -> None:
        self.store.set(
            _PROPOSAL_PREFIX + prop.id.to_bytes(8, "big"), prop.to_json()
        )

    def proposal(self, pid: int) -> Optional[Proposal]:
        raw = self.store.get(_PROPOSAL_PREFIX + pid.to_bytes(8, "big"))
        return Proposal.from_json(raw) if raw else None

    def proposals(self) -> List[Proposal]:
        return [
            Proposal.from_json(v)
            for _, v in self.store.iterate(_PROPOSAL_PREFIX)
        ]
