"""x/blobstream (QGB): EVM-bridge attestations over data roots.

Parity with /root/reference/x/blobstream/: the EndBlocker emits Valset
attestations on >5% power change or unbonding (abci.go:86-130) and
DataCommitment attestations every DataCommitmentWindow blocks
(abci.go:37-83, handleDataCommitmentRequest); attestations older than
~3 weeks are pruned (abci.go:20,134+); validators register EVM addresses
(MsgRegisterEVMAddress); the data-commitment root is a merkle root over the
block data roots in the window (served to EVM light clients).  Staking hooks
request a valset when validators are created or begin unbonding
(keeper/hooks.go:24-43).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.state.params import ParamsKeeper
from celestia_tpu.state.staking import StakingKeeper
from celestia_tpu.state.store import KVStore
from celestia_tpu.ops.nmt import rfc6962_root_np

_ATTESTATION_PREFIX = b"att/"
_LATEST_NONCE_KEY = b"latest_nonce"
_LAST_PRUNED_KEY = b"last_pruned_nonce"
_EVM_PREFIX = b"evm/"
_VALSET_REQUEST_KEY = b"valset_requested"

ATTESTATION_EXPIRY_NS = 3 * 7 * 24 * 3600 * 10**9  # 3 weeks
SIGNIFICANT_POWER_DIFF_PPM = 50_000  # 5%


@dataclass(frozen=True)
class BridgeValidator:
    evm_address: bytes
    power: int


@dataclass(frozen=True)
class Valset:
    nonce: int
    members: Tuple[BridgeValidator, ...]
    height: int
    time_ns: int

    TYPE = "valset"

    def to_json(self) -> dict:
        return {
            "type": self.TYPE,
            "nonce": self.nonce,
            "members": [
                {"evm_address": m.evm_address.hex(), "power": m.power}
                for m in self.members
            ],
            "height": self.height,
            "time_ns": self.time_ns,
        }


@dataclass(frozen=True)
class DataCommitment:
    nonce: int
    begin_block: int  # inclusive
    end_block: int  # exclusive
    data_root_tuple_root: bytes  # merkle over (height, dataRoot) tuples
    height: int
    time_ns: int

    TYPE = "data_commitment"

    def to_json(self) -> dict:
        return {
            "type": self.TYPE,
            "nonce": self.nonce,
            "begin_block": self.begin_block,
            "end_block": self.end_block,
            "data_root_tuple_root": self.data_root_tuple_root.hex(),
            "height": self.height,
            "time_ns": self.time_ns,
        }


def data_root_tuple_root(heights_and_roots: List[Tuple[int, bytes]]) -> bytes:
    """Merkle root over (height, data_root) tuples — what the EVM bridge
    verifies inclusion against (x/blobstream query server's root)."""
    leaves = [h.to_bytes(8, "big") + root for h, root in heights_and_roots]
    return rfc6962_root_np(leaves).tobytes()


class BlobstreamKeeper:
    def __init__(self, store: KVStore, staking: StakingKeeper, params: ParamsKeeper):
        self.store = store
        self.staking = staking
        self.params = params
        staking.hooks_after_validator_created.append(self._request_valset)
        staking.hooks_after_unbonding_initiated.append(self._request_valset)

    # --- EVM address registry ---------------------------------------------

    def register_evm_address(self, validator: bytes, evm_address: bytes) -> None:
        if self.staking.validator(validator) is None:
            raise ValueError(f"unknown validator {validator.hex()}")
        if len(evm_address) != 20:
            raise ValueError("EVM address must be 20 bytes")
        self.store.set(_EVM_PREFIX + validator, evm_address)

    def evm_address(self, validator: bytes) -> bytes:
        """Registered address, or a deterministic default derived from the
        validator address (reference defaults to a derived address)."""
        raw = self.store.get(_EVM_PREFIX + validator)
        if raw is not None:
            return raw
        return hashlib.sha256(b"default-evm/" + validator).digest()[:20]

    # --- attestations -----------------------------------------------------

    def latest_nonce(self) -> int:
        raw = self.store.get(_LATEST_NONCE_KEY)
        return int.from_bytes(raw, "big") if raw else 0

    def _next_nonce(self) -> int:
        n = self.latest_nonce() + 1
        self.store.set(_LATEST_NONCE_KEY, n.to_bytes(8, "big"))
        return n

    def _store_attestation(self, nonce: int, att: dict) -> None:
        self.store.set(
            _ATTESTATION_PREFIX + nonce.to_bytes(8, "big"),
            json.dumps(att, sort_keys=True).encode(),
        )

    def attestation(self, nonce: int) -> Optional[dict]:
        raw = self.store.get(_ATTESTATION_PREFIX + nonce.to_bytes(8, "big"))
        return json.loads(raw) if raw else None

    def attestations(self) -> List[dict]:
        return [
            json.loads(v) for _, v in self.store.iterate(_ATTESTATION_PREFIX)
        ]

    def _current_bridge_valset(self) -> Tuple[BridgeValidator, ...]:
        members = []
        for v in self.staking.bonded_validators():
            members.append(BridgeValidator(self.evm_address(v.operator), v.power))
        return tuple(sorted(members, key=lambda m: (-m.power, m.evm_address)))

    def _request_valset(self, _operator: bytes) -> None:
        self.store.set(_VALSET_REQUEST_KEY, b"\x01")

    def _last_valset(self) -> Optional[dict]:
        for att in reversed(self.attestations()):
            if att.get("type") == Valset.TYPE:
                return att
        return None

    @staticmethod
    def _power_diff_ppm(old_members: List[dict], new: Tuple[BridgeValidator, ...]) -> int:
        """Normalized power-vector L1 distance in ppm (abci.go power diff).

        Integer arithmetic only — this feeds a consensus decision (whether a
        valset attestation is emitted), so it must be bit-identical on every
        validator.
        """
        old_total = sum(m["power"] for m in old_members) or 1
        new_total = sum(m.power for m in new) or 1
        old_map = {m["evm_address"]: m["power"] for m in old_members}
        new_map = {m.evm_address.hex(): m.power for m in new}
        keys = set(old_map) | set(new_map)
        num = sum(
            abs(old_map.get(k, 0) * new_total - new_map.get(k, 0) * old_total)
            for k in keys
        )
        return num * 1_000_000 // (2 * old_total * new_total)

    def end_blocker(self, height: int, time_ns: int) -> List[dict]:
        """abci.go:29-35: emit valset/data-commitment attestations, prune."""
        emitted: List[dict] = []
        # -- valset (abci.go:86-130)
        current = self._current_bridge_valset()
        last = self._last_valset()
        requested = self.store.get(_VALSET_REQUEST_KEY) is not None
        need = False
        if current:
            if last is None or requested:
                need = True
            elif self._power_diff_ppm(last["members"], current) > SIGNIFICANT_POWER_DIFF_PPM:
                need = True
        if need:
            vs = Valset(self._next_nonce(), current, height, time_ns)
            self._store_attestation(vs.nonce, vs.to_json())
            emitted.append(vs.to_json())
            self.store.delete(_VALSET_REQUEST_KEY)
        # -- data commitment (abci.go:37-83): window boundary
        window = self.params.get("blobstream", "DataCommitmentWindow", 400)
        if height > 0 and height % window == 0:
            begin = height - window + 1
            end = height + 1
            dc_root = self._window_root(begin, end)
            dc = DataCommitment(self._next_nonce(), begin, end, dc_root, height, time_ns)
            self._store_attestation(dc.nonce, dc.to_json())
            emitted.append(dc.to_json())
        # -- prune expired (3 weeks)
        self._prune(time_ns)
        return emitted

    # data roots per height are recorded by the app after each block
    _DATA_ROOT_PREFIX = b"droot/"

    def record_data_root(self, height: int, data_root: bytes) -> None:
        self.store.set(self._DATA_ROOT_PREFIX + height.to_bytes(8, "big"), data_root)

    def data_root(self, height: int) -> Optional[bytes]:
        return self.store.get(self._DATA_ROOT_PREFIX + height.to_bytes(8, "big"))

    def _window_root(self, begin: int, end: int) -> bytes:
        tuples = []
        for h in range(begin, end):
            root = self.data_root(h)
            if root is None:
                root = b"\x00" * 32
            tuples.append((h, root))
        return data_root_tuple_root(tuples)

    # --- query/verify surface (x/blobstream query server + client/verify.go)

    def data_commitment_for_height(self, height: int) -> Optional[dict]:
        """DataCommitmentRangeForHeight parity
        (keeper/query_data_commitment.go): the DataCommitment attestation
        whose [begin_block, end_block) window covers ``height``."""
        for att in self.attestations():
            if att.get("type") != DataCommitment.TYPE:
                continue
            if att["begin_block"] <= height < att["end_block"]:
                return att
        return None

    def data_root_inclusion_proof(
        self, height: int, begin: int, end: int
    ) -> dict:
        """Merkle proof that block ``height``'s (height, data_root) tuple
        is a leaf of the [begin, end) window's data-root tuple root — the
        proof an EVM relayer posts against the Blobstream contract
        (client/verify.go DataRootInclusionProof role).  Serialized
        JSON-safe; verify with client/blobstream.verify_data_root_inclusion."""
        from celestia_tpu.da.proof import merkle_proof

        if not (begin <= height < end):
            raise ValueError(
                f"height {height} outside the window [{begin}, {end})"
            )
        # only ATTESTED windows are provable: this is reachable from an
        # unauthenticated query route, and an arbitrary [begin, end)
        # would let a remote caller size the loop below at will
        att = self.data_commitment_for_height(height)
        if att is None or att["begin_block"] != begin or (
            att["end_block"] != end
        ):
            raise ValueError(
                f"[{begin}, {end}) is not an attested DataCommitment window"
            )
        leaves = []
        target_root: Optional[bytes] = None
        for h in range(begin, end):
            root = self.data_root(h) or b"\x00" * 32
            if h == height:
                target_root = root
            leaves.append(h.to_bytes(8, "big") + root)
        proof = merkle_proof(leaves, height - begin)
        return {
            "height": height,
            "begin_block": begin,
            "end_block": end,
            "data_root": target_root.hex(),
            "index": proof.index,
            "total": proof.total,
            "aunts": [a.hex() for a in proof.aunts],
            "tuple_root": rfc6962_root_np(leaves).tobytes().hex(),
        }

    def _prune(self, now_ns: int) -> None:
        for _, raw in list(self.store.iterate(_ATTESTATION_PREFIX)):
            att = json.loads(raw)
            if now_ns - att["time_ns"] > ATTESTATION_EXPIRY_NS:
                self.store.delete(
                    _ATTESTATION_PREFIX + att["nonce"].to_bytes(8, "big")
                )
