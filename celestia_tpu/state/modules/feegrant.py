"""x/feegrant equivalent: fee allowances (granter pays a grantee's tx fees).

Parity role: cosmos-sdk feegrant keeper as wired into the reference's ante
chain (NewDeductFeeDecorator(accountKeeper, bankKeeper, feegrantKeeper, ...),
/root/reference/app/ante/ante.go:60-62).  Two allowance kinds mirror the
SDK's: BasicAllowance (optional one-shot spend limit + optional expiration)
and PeriodicAllowance (a basic envelope plus a per-period budget that
refills every period).

All amounts are integer utia; all times are integer nanoseconds — the same
decimal-determinism rule the rest of the state machine follows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.store import KVStore

_GRANT_PREFIX = b"fg/"

KIND_BASIC = 0
KIND_PERIODIC = 1


class FeeGrantError(ValueError):
    pass


@dataclass
class Allowance:
    """One allowance record.  spend_limit/expiration of 0 mean "unset"
    (an explicit zero-limit grant is meaningless and rejected on grant)."""

    kind: int = KIND_BASIC
    spend_limit: int = 0  # 0 = unlimited
    expiration_ns: int = 0  # 0 = never expires
    # periodic-only fields
    period_ns: int = 0
    period_spend_limit: int = 0
    period_can_spend: int = 0
    period_reset_ns: int = 0

    def marshal(self) -> bytes:
        out = bytearray()
        out += _varint(self.kind)
        out += _varint(self.spend_limit)
        out += _varint(self.expiration_ns)
        out += _varint(self.period_ns)
        out += _varint(self.period_spend_limit)
        out += _varint(self.period_can_spend)
        out += _varint(self.period_reset_ns)
        return bytes(out)

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Allowance":
        pos = 0
        kind, pos = _read_varint(raw, pos)
        spend, pos = _read_varint(raw, pos)
        exp, pos = _read_varint(raw, pos)
        pns, pos = _read_varint(raw, pos)
        plim, pos = _read_varint(raw, pos)
        pcan, pos = _read_varint(raw, pos)
        prst, pos = _read_varint(raw, pos)
        return cls(kind, spend, exp, pns, plim, pcan, prst)


class FeeGrantKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    # -- grant lifecycle ------------------------------------------------

    def grant(self, granter: bytes, grantee: bytes, allowance: Allowance) -> None:
        if granter == grantee:
            raise FeeGrantError("cannot self-grant a fee allowance")
        if self.get(granter, grantee) is not None:
            raise FeeGrantError("fee allowance already exists; revoke it first")
        if allowance.kind == KIND_PERIODIC:
            if allowance.period_ns <= 0 or allowance.period_spend_limit <= 0:
                raise FeeGrantError("periodic allowance needs period and limit")
            allowance.period_can_spend = allowance.period_spend_limit
        elif allowance.kind != KIND_BASIC:
            raise FeeGrantError(f"unknown allowance kind {allowance.kind}")
        self.store.set(_GRANT_PREFIX + granter + grantee, allowance.marshal())

    def revoke(self, granter: bytes, grantee: bytes) -> None:
        key = _GRANT_PREFIX + granter + grantee
        if self.store.get(key) is None:
            raise FeeGrantError("fee allowance not found")
        self.store.delete(key)

    def get(self, granter: bytes, grantee: bytes) -> Optional[Allowance]:
        raw = self.store.get(_GRANT_PREFIX + granter + grantee)
        return Allowance.unmarshal(raw) if raw is not None else None

    def grants_by_granter(self, granter: bytes) -> List[Tuple[bytes, Allowance]]:
        return [
            (k[len(_GRANT_PREFIX) + 20 :], Allowance.unmarshal(v))
            for k, v in self.store.iterate(_GRANT_PREFIX + granter)
        ]

    # -- the ante-chain entry point ------------------------------------

    def use_grant(
        self, granter: bytes, grantee: bytes, fee: int, now_ns: int
    ) -> None:
        """Accept or reject spending `fee` from the allowance; mutates the
        record (SDK Allowance.Accept semantics).  Expired or exhausted
        allowances are pruned on touch."""
        key = _GRANT_PREFIX + granter + grantee
        allowance = self.get(granter, grantee)
        if allowance is None:
            raise FeeGrantError(
                f"no fee allowance from {granter.hex()} to {grantee.hex()}"
            )
        if allowance.expiration_ns and now_ns >= allowance.expiration_ns:
            self.store.delete(key)
            raise FeeGrantError("fee allowance expired")
        if allowance.kind == KIND_PERIODIC:
            # refill the period budget if one or more periods elapsed
            if now_ns >= allowance.period_reset_ns:
                allowance.period_can_spend = allowance.period_spend_limit
                reset = allowance.period_reset_ns or now_ns
                while reset <= now_ns:
                    reset += allowance.period_ns
                allowance.period_reset_ns = reset
            if fee > allowance.period_can_spend:
                raise FeeGrantError(
                    f"fee {fee}utia exceeds period budget "
                    f"{allowance.period_can_spend}utia"
                )
            allowance.period_can_spend -= fee
        if allowance.spend_limit:
            if fee > allowance.spend_limit:
                raise FeeGrantError(
                    f"fee {fee}utia exceeds allowance {allowance.spend_limit}utia"
                )
            allowance.spend_limit -= fee
            if allowance.spend_limit == 0:
                # fully spent basic allowance is removed (SDK `remove` flag)
                self.store.delete(key)
                return
        self.store.set(key, allowance.marshal())
