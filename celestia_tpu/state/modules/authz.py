"""x/authz equivalent: message-execution grants (granter authorizes a
grantee to execute messages on its behalf).

Parity role: the cosmos-sdk authz keeper the reference wires at
/root/reference/app/app.go:292-294 (authzkeeper.NewKeeper + msg service
router).  Two authorization shapes mirror the SDK's: GenericAuthorization
(any message of a declared type) and SendAuthorization (bank sends up to a
decrementing spend limit).  MsgExec carries the wrapped inner messages; the
app dispatches each through its normal handler after the grant check, so an
exec'd message is indistinguishable from a directly-signed one at the
keeper layer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.store import KVStore

_GRANT_PREFIX = b"az/"


class AuthzError(ValueError):
    pass


@dataclass
class Authorization:
    """One grant record keyed by (granter, grantee, msg_type).

    spend_limit is only meaningful for MsgSend grants (SendAuthorization);
    0 = unlimited (GenericAuthorization semantics)."""

    msg_type: int  # Msg.TYPE id
    spend_limit: int = 0
    expiration_ns: int = 0  # 0 = never expires

    def marshal(self) -> bytes:
        return bytes(
            _varint(self.msg_type)
            + _varint(self.spend_limit)
            + _varint(self.expiration_ns)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Authorization":
        pos = 0
        t, pos = _read_varint(raw, pos)
        lim, pos = _read_varint(raw, pos)
        exp, pos = _read_varint(raw, pos)
        return cls(t, lim, exp)


class AuthzKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def _key(self, granter: bytes, grantee: bytes, msg_type: int) -> bytes:
        return _GRANT_PREFIX + granter + grantee + _varint(msg_type)

    def grant(self, granter: bytes, grantee: bytes, auth: Authorization) -> None:
        if granter == grantee:
            raise AuthzError("cannot self-grant an authorization")
        self.store.set(self._key(granter, grantee, auth.msg_type), auth.marshal())

    def revoke(self, granter: bytes, grantee: bytes, msg_type: int) -> None:
        key = self._key(granter, grantee, msg_type)
        if self.store.get(key) is None:
            raise AuthzError("authorization not found")
        self.store.delete(key)

    def get(
        self, granter: bytes, grantee: bytes, msg_type: int
    ) -> Optional[Authorization]:
        raw = self.store.get(self._key(granter, grantee, msg_type))
        return Authorization.unmarshal(raw) if raw is not None else None

    def grants_by_granter(self, granter: bytes) -> List[Tuple[bytes, Authorization]]:
        return [
            (k[len(_GRANT_PREFIX) + 20 : len(_GRANT_PREFIX) + 40],
             Authorization.unmarshal(v))
            for k, v in self.store.iterate(_GRANT_PREFIX + granter)
        ]

    def check_and_consume(
        self,
        granter: bytes,
        grantee: bytes,
        msg,
        now_ns: int,
    ) -> None:
        """Authorize one inner message of a MsgExec; mutates spend limits
        (SDK Authorization.Accept).  Raises AuthzError when the grant is
        missing, expired, or exhausted."""
        key = self._key(granter, grantee, msg.TYPE)
        auth = self.get(granter, grantee, msg.TYPE)
        if auth is None:
            raise AuthzError(
                f"no authorization for msg type {type(msg).__name__} from "
                f"{granter.hex()} to {grantee.hex()}"
            )
        if auth.expiration_ns and now_ns >= auth.expiration_ns:
            self.store.delete(key)
            raise AuthzError("authorization expired")
        if auth.spend_limit:
            amount = getattr(msg, "amount", None)
            if amount is None:
                raise AuthzError(
                    "spend-limited authorization on a message without an amount"
                )
            if amount > auth.spend_limit:
                raise AuthzError(
                    f"amount {amount}utia exceeds authorization "
                    f"{auth.spend_limit}utia"
                )
            auth.spend_limit -= amount
            if auth.spend_limit == 0:
                self.store.delete(key)
                return
            self.store.set(key, auth.marshal())
