"""Minimal IBC transfer stack: channels, ICS-20 app module, middleware.

Role: the transport x/tokenfilter mounts on.  The reference wires its
middleware into ibc-go's transfer stack (app/app.go:71-78,
x/tokenfilter/ibc_middleware.go:38-80); here the same three layers exist
natively:

  ChannelKeeper  — channel registry, send/recv sequences, packet
                   commitments and acknowledgements (ICS-4 surface).
  TransferModule — ICS-20 escrow/mint semantics: native tokens escrow on
                   send and unescrow on return; foreign tokens would mint
                   prefixed vouchers on receive (on Celestia the token
                   filter forbids that branch); error acks refund.
  middleware     — any wrapper implementing on_recv_packet; the token
                   filter middleware rejects foreign tokens with an error
                   acknowledgement BEFORE the transfer module can mint.

An in-process Relayer connects two stacks for tests (the shape of ibc-go's
testing chains used by the reference's test/tokenfilter suite).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.state.modules.tokenfilter import (
    Acknowledgement,
    FungibleTokenPacketData,
    NATIVE_DENOM,
    Packet,
    on_recv_packet as tokenfilter_policy,
)

TRANSFER_PORT = "transfer"


def escrow_address(port: str, channel: str) -> bytes:
    """Deterministic per-channel escrow account (ics20 escrow address)."""
    return hashlib.sha256(f"ics20-escrow/{port}/{channel}".encode()).digest()[:20]


@dataclass
class Channel:
    channel_id: str
    port: str
    counterparty_channel: str
    counterparty_port: str
    state: str = "OPEN"


class ChannelKeeper:
    """ICS-4 surface: channels, sequences, commitments, acks."""

    def __init__(self):
        self.channels: Dict[str, Channel] = {}
        self._next_seq: Dict[str, int] = {}
        self.commitments: Dict[Tuple[str, int], bytes] = {}
        self.acks: Dict[Tuple[str, int], Acknowledgement] = {}

    def open_channel(
        self, channel_id: str, counterparty_channel: str,
        port: str = TRANSFER_PORT, counterparty_port: str = TRANSFER_PORT,
    ) -> Channel:
        ch = Channel(channel_id, port, counterparty_channel, counterparty_port)
        self.channels[channel_id] = ch
        self._next_seq[channel_id] = 1
        return ch

    def send_packet(self, channel_id: str, data: bytes) -> Tuple[Packet, int]:
        ch = self.channels.get(channel_id)
        if ch is None or ch.state != "OPEN":
            raise ValueError(f"channel {channel_id} is not open")
        seq = self._next_seq[channel_id]
        self._next_seq[channel_id] = seq + 1
        packet = Packet(
            source_port=ch.port,
            source_channel=ch.channel_id,
            dest_port=ch.counterparty_port,
            dest_channel=ch.counterparty_channel,
            data=data,
        )
        self.commitments[(channel_id, seq)] = hashlib.sha256(data).digest()
        return packet, seq

    def write_ack(self, channel_id: str, seq: int, ack: Acknowledgement) -> None:
        self.acks[(channel_id, seq)] = ack

    def delete_commitment(self, channel_id: str, seq: int) -> None:
        self.commitments.pop((channel_id, seq), None)


class TransferModule:
    """ICS-20 application module over a denom-aware bank."""

    def __init__(self, bank, channels: ChannelKeeper, chain_name: str = "chain"):
        self.bank = bank
        self.channels = channels
        self.chain_name = chain_name

    # -- send side -----------------------------------------------------

    def send_transfer(
        self,
        sender: bytes,
        receiver: str,
        amount: int,
        denom: str,
        channel_id: str,
    ) -> Tuple[Packet, int]:
        ch = self.channels.channels.get(channel_id)
        if ch is None:
            raise ValueError(f"unknown channel {channel_id}")
        prefix = f"{ch.port}/{ch.channel_id}/"
        if denom.startswith(prefix):
            # voucher going home: burn it here (the counterparty unescrows)
            self.bank.burn_denom(sender, amount, denom)
        else:
            # source-chain token: escrow it
            self.bank.send_denom(
                sender, escrow_address(ch.port, ch.channel_id), amount, denom
            )
        data = FungibleTokenPacketData(
            denom=denom,
            amount=str(amount),
            sender=sender.hex(),
            receiver=receiver,
        ).to_json()
        return self.channels.send_packet(channel_id, data)

    # -- receive side --------------------------------------------------

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        try:
            data = FungibleTokenPacketData.from_json(packet.data)
            amount = int(data.amount)
            receiver = bytes.fromhex(data.receiver)
        except (ValueError, KeyError):
            return Acknowledgement(False, "cannot unmarshal ICS-20 packet data")
        prefix = f"{packet.source_port}/{packet.source_channel}/"
        try:
            if data.denom.startswith(prefix):
                # token returning to its source: unescrow the original
                base = data.denom[len(prefix):]
                self.bank.send_denom(
                    escrow_address(packet.dest_port, packet.dest_channel),
                    receiver, amount, base,
                )
            else:
                # foreign token: mint a voucher with this hop's prefix
                voucher = (
                    f"{packet.dest_port}/{packet.dest_channel}/{data.denom}"
                )
                self.bank.mint_denom(receiver, amount, voucher)
        except ValueError as e:
            return Acknowledgement(False, str(e))
        return Acknowledgement(True)

    # -- ack / refund --------------------------------------------------

    def on_acknowledgement(
        self, packet: Packet, seq: int, ack: Acknowledgement
    ) -> None:
        self.channels.delete_commitment(packet.source_channel, seq)
        if ack.success:
            return
        # refund: reverse the send-side escrow/burn
        try:
            data = FungibleTokenPacketData.from_json(packet.data)
        except (ValueError, KeyError):
            return
        sender = bytes.fromhex(data.sender)
        amount = int(data.amount)
        prefix = f"{packet.source_port}/{packet.source_channel}/"
        if data.denom.startswith(prefix):
            self.bank.mint_denom(sender, amount, data.denom)  # re-mint voucher
        else:
            self.bank.send_denom(
                escrow_address(packet.source_port, packet.source_channel),
                sender, amount, data.denom,
            )


class TokenFilterMiddleware:
    """tokenFilterMiddleware parity (ibc_middleware.go:38-80): wraps an IBC
    app module; foreign tokens get an error acknowledgement and NEVER reach
    the wrapped module's mint path."""

    def __init__(self, app_module: TransferModule):
        self.app = app_module

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        verdict = tokenfilter_policy(packet)
        if not verdict.success:
            return verdict
        return self.app.on_recv_packet(packet)

    def __getattr__(self, name):
        return getattr(self.app, name)


@dataclass
class IBCStack:
    """One chain's transfer stack: channels + (possibly wrapped) module."""

    name: str
    bank: object
    channels: ChannelKeeper = field(default_factory=ChannelKeeper)
    filtered: bool = False

    def __post_init__(self):
        module = TransferModule(self.bank, self.channels, self.name)
        self.module = TokenFilterMiddleware(module) if self.filtered else module


class Relayer:
    """In-process packet relay between two stacks (ibc-go testing shape)."""

    def __init__(self, a: IBCStack, b: IBCStack,
                 channel_a: str = "channel-0", channel_b: str = "channel-0"):
        self.a, self.b = a, b
        self.channel_a, self.channel_b = channel_a, channel_b
        a.channels.open_channel(channel_a, channel_b)
        b.channels.open_channel(channel_b, channel_a)

    def relay(self, src: IBCStack, packet: Packet, seq: int) -> Acknowledgement:
        dst = self.b if src is self.a else self.a
        ack = dst.module.on_recv_packet(packet)
        dst.channels.write_ack(packet.dest_channel, seq, ack)
        src.module.on_acknowledgement(packet, seq, ack)
        return ack
