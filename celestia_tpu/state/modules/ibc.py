"""Minimal IBC transfer stack: channels, ICS-20 app module, middleware.

Role: the transport x/tokenfilter mounts on.  The reference wires its
middleware into ibc-go's transfer stack (app/app.go:71-78,
x/tokenfilter/ibc_middleware.go:38-80); here the same three layers exist
natively:

  ChannelKeeper  — channel registry, send/recv sequences, packet
                   commitments and acknowledgements (ICS-4 surface).
  TransferModule — ICS-20 escrow/mint semantics: native tokens escrow on
                   send and unescrow on return; foreign tokens would mint
                   prefixed vouchers on receive (on Celestia the token
                   filter forbids that branch); error acks refund.
  middleware     — any wrapper implementing on_recv_packet; the token
                   filter middleware rejects foreign tokens with an error
                   acknowledgement BEFORE the transfer module can mint.

An in-process Relayer connects two stacks for tests (the shape of ibc-go's
testing chains used by the reference's test/tokenfilter suite).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.state.modules.tokenfilter import (
    Acknowledgement,
    FungibleTokenPacketData,
    NATIVE_DENOM,
    Packet,
    on_recv_packet as tokenfilter_policy,
)

TRANSFER_PORT = "transfer"


def escrow_address(port: str, channel: str) -> bytes:
    """Deterministic per-channel escrow account (ics20 escrow address)."""
    return hashlib.sha256(f"ics20-escrow/{port}/{channel}".encode()).digest()[:20]


@dataclass
class Channel:
    channel_id: str
    port: str
    counterparty_channel: str
    counterparty_port: str
    state: str = "OPEN"


class ChannelKeeper:
    """ICS-4 surface: channels, sequences, commitments, acks.

    With a KVStore attached (the app's "ibc" substore), every packet
    commitment, acknowledgement and receive receipt is ALSO written to
    merkleized state — which is what makes them PROVABLE to a
    counterparty light client (state.merkle proofs over the committed
    app hash; modules/ibc_client.py key layout).  Without a store the
    keeper works dict-only (standalone test stacks)."""

    def __init__(self, store=None):
        self.store = store
        self.channels: Dict[str, Channel] = {}
        self._next_seq: Dict[str, int] = {}
        self.commitments: Dict[Tuple[str, int], bytes] = {}
        self.acks: Dict[Tuple[str, int], Acknowledgement] = {}
        # outbound log relayers drain (packet-forward hops emit sends the
        # caller never sees, so the transport surfaces them here)
        self.sent: List[Tuple[Packet, int]] = []
        self._timed_out: set = set()
        self._received: set = set()

    def _skeys(self):
        from celestia_tpu.state.modules import ibc_client as keys

        return keys

    def rehydrate(self) -> None:
        """Rebuild the in-memory guards from the merkleized store after a
        snapshot/disk restore: receipts (replay protection), commitments
        (ack/timeout claims), channels, send sequences and timed-out
        marks all survive a restart because they were mirrored to state —
        without this, a restored node would accept replays and refuse
        legitimate acks."""
        if self.store is None:
            return
        for k, v in self.store.iterate():
            parts = k.decode().split("/")
            if parts[0] == "channels" and len(parts) == 2:
                d = json.loads(v)
                self.channels[parts[1]] = Channel(
                    parts[1], d["port"], d["counterparty_channel"],
                    d["counterparty_port"], d["state"],
                )
                self._next_seq.setdefault(parts[1], 1)
            elif parts[0] == "nextseq" and len(parts) == 2:
                self._next_seq[parts[1]] = int.from_bytes(v, "big")
            elif parts[0] == "commitments" and len(parts) == 3:
                self.commitments[(parts[1], int(parts[2]))] = v
            elif parts[0] == "receipts" and len(parts) == 3:
                self._received.add((parts[1], int(parts[2])))
            elif parts[0] == "timedout" and len(parts) == 3:
                self._timed_out.add((parts[1], int(parts[2])))

    def open_channel(
        self, channel_id: str, counterparty_channel: str,
        port: str = TRANSFER_PORT, counterparty_port: str = TRANSFER_PORT,
    ) -> Channel:
        ch = Channel(channel_id, port, counterparty_channel, counterparty_port)
        self.channels[channel_id] = ch
        self._next_seq[channel_id] = 1
        if self.store is not None:
            keys = self._skeys()
            self.store.set(
                keys.channel_key(channel_id),
                json.dumps(
                    {
                        "port": port,
                        "counterparty_channel": counterparty_channel,
                        "counterparty_port": counterparty_port,
                        "state": ch.state,
                    }
                ).encode(),
            )
        return ch

    def send_packet(
        self, channel_id: str, data: bytes, timeout_height: int = 0
    ) -> Tuple[Packet, int]:
        ch = self.channels.get(channel_id)
        if ch is None or ch.state != "OPEN":
            raise ValueError(f"channel {channel_id} is not open")
        seq = self._next_seq[channel_id]
        self._next_seq[channel_id] = seq + 1
        packet = Packet(
            source_port=ch.port,
            source_channel=ch.channel_id,
            dest_port=ch.counterparty_port,
            dest_channel=ch.counterparty_channel,
            data=data,
            timeout_height=timeout_height,
        )
        keys = self._skeys()
        commitment = keys.packet_commitment(data, timeout_height)
        self.commitments[(channel_id, seq)] = commitment
        if self.store is not None:
            self.store.set(keys.commitment_key(channel_id, seq), commitment)
            self.store.set(
                keys.nextseq_key(channel_id),
                self._next_seq[channel_id].to_bytes(8, "big"),
            )
        self.sent.append((packet, seq))
        return packet, seq

    def write_ack(self, channel_id: str, seq: int, ack: Acknowledgement) -> None:
        self.acks[(channel_id, seq)] = ack
        if self.store is not None:
            keys = self._skeys()
            self.store.set(
                keys.ack_key(channel_id, seq),
                hashlib.sha256(keys.ack_bytes(ack)).digest(),
            )

    def write_receipt(self, channel_id: str, seq: int) -> None:
        """Replay guard: one receive per (channel, seq), provable."""
        if (channel_id, seq) in self._received:
            raise ValueError(
                f"packet {channel_id}#{seq} was already received"
            )
        self._received.add((channel_id, seq))
        if self.store is not None:
            self.store.set(
                self._skeys().receipt_key(channel_id, seq), b"\x01"
            )

    def has_receipt(self, channel_id: str, seq: int) -> bool:
        return (channel_id, seq) in self._received

    def claim_commitment(
        self, channel_id: str, seq: int, data: bytes, timeout_height: int = 0
    ) -> None:
        """Check-and-delete: the stored commitment must exist and match the
        packet data + timeout (ibc-go's AcknowledgePacket/TimeoutPacket
        verify the same before the app callback).  A missing commitment
        means the packet's lifecycle already completed — acting on it
        again would refund twice, so this RAISES instead of silently
        ignoring."""
        key = (channel_id, seq)
        stored = self.commitments.get(key)
        if stored is None:
            raise ValueError(
                f"no commitment for packet {channel_id}#{seq}: already "
                f"acked or timed out"
            )
        if stored != self._skeys().packet_commitment(data, timeout_height):
            raise ValueError(f"commitment mismatch for packet {channel_id}#{seq}")
        del self.commitments[key]
        if self.store is not None:
            self.store.delete(self._skeys().commitment_key(channel_id, seq))

    # sequences whose timeout was processed: a late delivery must refuse
    # (the source already refunded)
    def mark_timed_out(self, channel_id: str, seq: int) -> None:
        self._timed_out.add((channel_id, seq))
        if self.store is not None:
            self.store.set(
                self._skeys().timedout_key(channel_id, seq), b"\x01"
            )

    def is_timed_out(self, channel_id: str, seq: int) -> bool:
        return (channel_id, seq) in self._timed_out


class TransferModule:
    """ICS-20 application module over a denom-aware bank."""

    def __init__(self, bank, channels: ChannelKeeper, chain_name: str = "chain"):
        self.bank = bank
        self.channels = channels
        self.chain_name = chain_name

    # -- send side -----------------------------------------------------

    def send_transfer(
        self,
        sender: bytes,
        receiver: str,
        amount: int,
        denom: str,
        channel_id: str,
        memo: str = "",
        timeout_height: int = 0,
    ) -> Tuple[Packet, int]:
        """memo rides inside the committed packet data (it carries
        packet-forward instructions, so it MUST be covered by the
        commitment — a relayer-injected memo would fail the claim)."""
        ch = self.channels.channels.get(channel_id)
        if ch is None:
            raise ValueError(f"unknown channel {channel_id}")
        prefix = f"{ch.port}/{ch.channel_id}/"
        if denom.startswith(prefix):
            # voucher going home: burn it here (the counterparty unescrows)
            self.bank.burn_denom(sender, amount, denom)
        else:
            # source-chain token: escrow it
            self.bank.send_denom(
                sender, escrow_address(ch.port, ch.channel_id), amount, denom
            )
        data = FungibleTokenPacketData(
            denom=denom,
            amount=str(amount),
            sender=sender.hex(),
            receiver=receiver,
            memo=memo,
        ).to_json()
        return self.channels.send_packet(channel_id, data, timeout_height)

    # -- receive side --------------------------------------------------

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        try:
            data = FungibleTokenPacketData.from_json(packet.data)
            amount = int(data.amount)
            receiver = bytes.fromhex(data.receiver)
        except (ValueError, KeyError):
            return Acknowledgement(False, "cannot unmarshal ICS-20 packet data")
        prefix = f"{packet.source_port}/{packet.source_channel}/"
        try:
            if data.denom.startswith(prefix):
                # token returning to its source: unescrow the original
                base = data.denom[len(prefix):]
                self.bank.send_denom(
                    escrow_address(packet.dest_port, packet.dest_channel),
                    receiver, amount, base,
                )
            else:
                # foreign token: mint a voucher with this hop's prefix
                voucher = (
                    f"{packet.dest_port}/{packet.dest_channel}/{data.denom}"
                )
                self.bank.mint_denom(receiver, amount, voucher)
        except ValueError as e:
            return Acknowledgement(False, str(e))
        return Acknowledgement(True)

    # -- ack / refund --------------------------------------------------

    def on_acknowledgement(
        self, packet: Packet, seq: int, ack: Acknowledgement
    ) -> None:
        # check-and-claim guards replay: a second ack (or ack-after-
        # timeout) raises instead of refunding twice
        self.channels.claim_commitment(
            packet.source_channel, seq, packet.data, packet.timeout_height
        )
        if ack.success:
            return
        self._refund(packet)

    def on_timeout_packet(self, packet: Packet, seq: int) -> None:
        """ICS-4 timeout: refund like an error ack (ibc-go's transfer
        OnTimeoutPacket).  The commitment claim rejects timeout-after-ack,
        double-timeout, and fabricated packets — the refund only ever
        fires once per real in-flight send."""
        self.channels.claim_commitment(
            packet.source_channel, seq, packet.data, packet.timeout_height
        )
        self._refund(packet)

    def _refund(self, packet: Packet) -> None:
        """Reverse the send-side escrow/burn."""
        try:
            data = FungibleTokenPacketData.from_json(packet.data)
        except (ValueError, KeyError):
            return
        sender = bytes.fromhex(data.sender)
        amount = int(data.amount)
        prefix = f"{packet.source_port}/{packet.source_channel}/"
        if data.denom.startswith(prefix):
            self.bank.mint_denom(sender, amount, data.denom)  # re-mint voucher
        else:
            self.bank.send_denom(
                escrow_address(packet.source_port, packet.source_channel),
                sender, amount, data.denom,
            )


class TokenFilterMiddleware:
    """tokenFilterMiddleware parity (ibc_middleware.go:38-80): wraps an IBC
    app module; foreign tokens get an error acknowledgement and NEVER reach
    the wrapped module's mint path."""

    def __init__(self, app_module: TransferModule):
        self.app = app_module

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        verdict = tokenfilter_policy(packet)
        if not verdict.success:
            return verdict
        return self.app.on_recv_packet(packet)

    def __getattr__(self, name):
        return getattr(self.app, name)


def forward_address(channel: str, receiver: str) -> bytes:
    """Deterministic intermediate account the forward hop settles through
    (packet-forward-middleware derives one the same way)."""
    return hashlib.sha256(f"pfm-intermediate/{channel}/{receiver}".encode()).digest()[:20]


class PacketForwardMiddleware:
    """packet-forward-middleware parity (the reference wires
    PacketForwardKeeper, app/app.go:219): an inbound ICS-20 packet whose
    memo carries {"forward": {"receiver", "channel"}} is received into a
    deterministic intermediate account and immediately re-sent out the
    requested channel toward the final receiver.  A failed onward send
    refunds by acking the ORIGINAL packet as an error, so the upstream
    chain's own refund path fires — the same fail-safe the real PFM uses."""

    def __init__(self, app_module, transfer: TransferModule):
        self.app = app_module  # next layer inward (e.g. token filter)
        self.transfer = transfer  # for the onward hop

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        try:
            data = FungibleTokenPacketData.from_json(packet.data)
            memo = json.loads(data.memo) if data.memo else {}
        except (ValueError, KeyError):
            return self.app.on_recv_packet(packet)
        fwd = memo.get("forward") if isinstance(memo, dict) else None
        if not fwd:
            return self.app.on_recv_packet(packet)
        try:
            final_receiver = fwd["receiver"]
            out_channel = fwd["channel"]
        except (KeyError, TypeError):
            return Acknowledgement(False, "malformed forward memo")
        # hop 1: receive into the intermediate account via the inner stack
        # (token filter still applies — a forbidden token never forwards)
        intermediate = forward_address(out_channel, final_receiver)
        hop_packet = Packet(
            packet.source_port, packet.source_channel,
            packet.dest_port, packet.dest_channel,
            FungibleTokenPacketData(
                data.denom, data.amount, data.sender, intermediate.hex(),
            ).to_json(),
        )
        ack = self.app.on_recv_packet(hop_packet)
        if not ack.success:
            return ack
        # hop 2: send onward; the denom as held HERE gains/loses the hop
        # prefix exactly as the transfer module's receive computed it
        prefix = f"{packet.source_port}/{packet.source_channel}/"
        if data.denom.startswith(prefix):
            local_denom = data.denom[len(prefix):]
        else:
            local_denom = f"{packet.dest_port}/{packet.dest_channel}/{data.denom}"
        try:
            self.transfer.send_transfer(
                intermediate, final_receiver, int(data.amount),
                local_denom, out_channel,
            )
        except ValueError as e:
            # onward hop failed: error-acking the original makes the sender
            # chain refund, so the hop-1 credit must leave circulation HERE
            # or the tokens exist on both chains (supply inflation)
            amount = int(data.amount)
            if data.denom.startswith(prefix):
                # hop 1 unescrowed a returning token: re-escrow it
                self.transfer.bank.send_denom(
                    intermediate,
                    escrow_address(packet.dest_port, packet.dest_channel),
                    amount, local_denom,
                )
            else:
                # hop 1 minted a voucher: burn it
                self.transfer.bank.burn_denom(intermediate, amount, local_denom)
            return Acknowledgement(False, f"forward failed: {e}")
        return Acknowledgement(True)

    def __getattr__(self, name):
        return getattr(self.app, name)


ICA_HOST_PORT = "icahost"
ICA_CONTROLLER_PORT = "icacontroller"


def interchain_account_address(connection: str, owner: str) -> bytes:
    """Deterministic ICS-27 interchain account address for (connection,
    controller-side owner)."""
    return hashlib.sha256(
        f"ics27-account/{connection}/{owner}".encode()
    ).digest()[:20]


class ICAControllerModule:
    """ICS-27 controller: drives interchain accounts on counterparty
    hosts (the other half of ICAHostModule; the reference wires only the
    host keeper, app/app.go:203 — the controller lives on the chains
    whose users act THROUGH Celestia-hosted accounts, and is provided
    here so two framework chains can pair up in tests and devnets)."""

    def __init__(self, channels: ChannelKeeper):
        self.channels = channels
        # (channel, seq) -> Acknowledgement once the host answered
        self.results: Dict[Tuple[str, int], Acknowledgement] = {}

    def interchain_address(self, connection: str, owner: str) -> bytes:
        """The account this owner controls on the host (same derivation)."""
        return interchain_account_address(connection, owner)

    def send_tx(
        self,
        owner: str,
        connection: str,
        channel_id: str,
        msgs: List,
    ) -> Tuple[Packet, int]:
        """Package msgs into an ica_tx packet on an icacontroller channel.
        Every msg must be signed-for by the owner's interchain account —
        the host enforces it too, but failing early here saves a round
        trip."""
        from celestia_tpu.state.tx import marshal_msg

        ch = self.channels.channels.get(channel_id)
        if (
            ch is None
            or ch.port != ICA_CONTROLLER_PORT
            or ch.counterparty_port != ICA_HOST_PORT
            or ch.state != "OPEN"
        ):
            raise ValueError(
                f"{channel_id} is not an open {ICA_CONTROLLER_PORT}->"
                f"{ICA_HOST_PORT} channel"
            )
        if not msgs:
            # ibc-go's ICS-27 rejects empty tx data; a success ack for a
            # no-op would mask the caller's empty-batch bug
            raise ValueError("ica_tx needs at least one message")
        ica = self.interchain_address(connection, owner)
        for m in msgs:
            if any(s != ica for s in m.signers()):
                raise ValueError(
                    "msg signer is not the owner's interchain account"
                )
        data = json.dumps(
            {
                "type": "ica_tx",
                "owner": owner,
                "connection": connection,
                "msgs": [marshal_msg(m).hex() for m in msgs],
            }
        ).encode()
        return self.channels.send_packet(channel_id, data)

    def on_acknowledgement(
        self, packet: Packet, seq: int, ack: Acknowledgement
    ) -> None:
        self.channels.claim_commitment(
            packet.source_channel, seq, packet.data, packet.timeout_height
        )
        self.results[(packet.source_channel, seq)] = ack

    def on_timeout_packet(self, packet: Packet, seq: int) -> None:
        self.channels.claim_commitment(
            packet.source_channel, seq, packet.data, packet.timeout_height
        )
        self.results[(packet.source_channel, seq)] = Acknowledgement(
            False, "packet timed out"
        )


class ICAHostModule:
    """ICS-27 host parity (the reference wires ICAHostKeeper,
    app/app.go:203): executes transactions sent by a counterparty
    controller chain under that controller's interchain account.

    Packet data: {"type": "ica_tx", "owner": ..., "connection": ...,
    "msgs": [hex-marshaled msgs]}.  Every msg's declared signer must BE the
    derived interchain account — a controller can never act as anyone else.
    Execution is atomic: any failure rolls back the whole packet and
    returns an error ack."""

    def __init__(self, app, allow_msgs: Optional[List[int]] = None):
        self.app = app  # the state-machine App (msg dispatch + stores)
        # host-side allowlist of msg TYPE ids (SDK ica host AllowMessages);
        # None = allow all registered msgs
        self.allow_msgs = allow_msgs

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        from celestia_tpu.state.ante import GasMeter
        from celestia_tpu.state.tx import unmarshal_msg

        try:
            d = json.loads(packet.data)
            assert d.get("type") == "ica_tx"
            owner = d["owner"]
            connection = d["connection"]
            raw_msgs = [bytes.fromhex(m) for m in d["msgs"]]
        except (ValueError, KeyError, AssertionError):
            return Acknowledgement(False, "cannot unmarshal ICS-27 packet data")
        ica_addr = interchain_account_address(connection, owner)
        msgs = []
        try:
            for raw in raw_msgs:
                msg, used = unmarshal_msg(raw)
                if used != len(raw):
                    raise ValueError("trailing bytes in ICA msg")
                msgs.append(msg)
        except ValueError as e:
            return Acknowledgement(False, f"bad ICA msg: {e}")
        for msg in msgs:
            if self.allow_msgs is not None and msg.TYPE not in self.allow_msgs:
                return Acknowledgement(
                    False, f"msg type {msg.TYPE} not allowed on this host"
                )
            if any(s != ica_addr for s in msg.signers()):
                return Acknowledgement(
                    False, "ICA msg signer is not the interchain account"
                )
        # atomic execution on a branch (ibc-go's cache-ctx commit shape)
        branch = self.app.store.branch()
        saved = self.app.store
        self.app.store = branch
        self.app._wire_keepers(rebuild_ibc=False)
        try:
            meter = GasMeter(10_000_000)
            for msg in msgs:
                self.app._execute_msg(msg, meter)
        except Exception as e:
            return Acknowledgement(False, f"ICA execution failed: {e}")
        else:
            saved.write_back(branch)
            return Acknowledgement(True)
        finally:
            self.app.store = saved
            self.app._wire_keepers(rebuild_ibc=False)


@dataclass
class IBCStack:
    """One chain's transfer stack: channels + middleware-wrapped module.

    Stack order (outermost first) mirrors the reference's app.go wiring:
    packet-forward -> token filter -> ICS-20 transfer; the ICS-27 host
    module listens on its own port when an App is attached."""

    name: str
    bank: object
    channels: ChannelKeeper = None
    filtered: bool = False
    forwarding: bool = True
    app: object = None  # the state-machine App (enables the ICA host)
    store: object = None  # the app's "ibc" KVStore (provable commitments)

    def __post_init__(self):
        if self.channels is None:
            self.channels = ChannelKeeper(store=self.store)
            # a restored node's guards come back from merkleized state
            self.channels.rehydrate()
        from celestia_tpu.state.modules.ibc_client import ConnectionKeeper

        # client state (valsets, consensus states, the frozen flag) and
        # channel bindings persist in the same "ibc" substore as the
        # channel keeper's receipts — a restored node's frozen client
        # stays frozen (disjoint key prefixes; ibc_client.rehydrate)
        self.connections = ConnectionKeeper(store=self.store)
        self.connections.rehydrate()
        transfer = TransferModule(self.bank, self.channels, self.name)
        module = TokenFilterMiddleware(transfer) if self.filtered else transfer
        if self.forwarding:
            module = PacketForwardMiddleware(module, transfer)
        self.module = module
        self.ica_host = ICAHostModule(self.app) if self.app is not None else None
        self.ica_controller = ICAControllerModule(self.channels)

    def rebind(self, store, bank) -> None:
        """Swap the underlying KVStore/bank handles without rebuilding or
        rescanning in-memory state.

        The deliver path branch-swaps the app's store around every tx
        (state/app.py _wire_keepers); rebuilding the stack there would
        pay a full "ibc" substore scan + JSON decode per tx for nothing —
        no msg mutates IBC in-memory state, so only the handles the next
        WRITE goes through need to move.  Full rebuilds (with rehydrate)
        remain the restore/import path."""
        self.store = store
        self.bank = bank
        self.channels.store = store
        self.connections.store = store
        for client in self.connections.clients.values():
            client.store = store
        # the one TransferModule instance is shared by every middleware
        # layer (token filter wraps it, PFM aliases it as .transfer)
        mod = self.module
        while mod is not None and not isinstance(mod, TransferModule):
            mod = getattr(mod, "app", None)
        if mod is not None:
            mod.bank = bank

    def on_recv_packet(self, packet: Packet) -> Acknowledgement:
        """Port-level dispatch (IBC router role)."""
        if packet.dest_port == ICA_HOST_PORT:
            if self.ica_host is None:
                return Acknowledgement(False, "ICA host not enabled")
            return self.ica_host.on_recv_packet(packet)
        return self.module.on_recv_packet(packet)

    def app_module_for(self, packet: Packet):
        """The module owning a packet's SOURCE port (ack/timeout router)."""
        if packet.source_port == ICA_CONTROLLER_PORT:
            return self.ica_controller
        return self.module


class Relayer:
    """In-process packet relay between two stacks (ibc-go testing shape)."""

    def __init__(self, a: IBCStack, b: IBCStack,
                 channel_a: str = "channel-0", channel_b: str = "channel-0"):
        self.a, self.b = a, b
        self.channel_a, self.channel_b = channel_a, channel_b
        a.channels.open_channel(channel_a, channel_b)
        b.channels.open_channel(channel_b, channel_a)

    def relay(self, src: IBCStack, packet: Packet, seq: int) -> Acknowledgement:
        dst = self.b if src is self.a else self.a
        if dst.channels.is_timed_out(packet.dest_channel, seq):
            # the source already refunded on timeout; executing the
            # receive now would double-credit — refuse outright
            raise ValueError(
                f"packet {packet.dest_channel}#{seq} timed out; receive refused"
            )
        ack = dst.on_recv_packet(packet)  # port-level router (ICA vs ICS-20)
        dst.channels.write_ack(packet.dest_channel, seq, ack)
        src.app_module_for(packet).on_acknowledgement(packet, seq, ack)
        return ack

    def timeout(self, src: IBCStack, packet: Packet, seq: int) -> None:
        """Relayer processes a timeout: the destination marks the sequence
        closed (a late delivery is refused from now on), then the source
        refunds — once, enforced by the commitment claim."""
        dst = self.b if src is self.a else self.a
        dst.channels.mark_timed_out(packet.dest_channel, seq)
        src.app_module_for(packet).on_timeout_packet(packet, seq)


def recv_packet_verified(
    stack: IBCStack, packet: Packet, seq: int, proof: dict, proof_height: int
) -> Acknowledgement:
    """Proof-gated receive (ibc-go core RecvPacket): before ANY app
    callback runs, the packet must be proven committed on the
    counterparty — a merkle membership proof of
    commitments/{source_channel}/{seq} == sha256(packet.data) in the
    counterparty's "ibc" store, verified against the light client bound
    to the destination channel.  A forged, tampered or replayed packet
    never reaches the transfer module.  Raises on verification failure
    (the relayer is misbehaving; there is nothing to ack)."""
    from celestia_tpu.state.modules.ibc_client import (
        ClientError,
        commitment_key,
    )

    client = stack.connections.client_for_channel(packet.dest_channel)
    if client is None:
        raise ClientError(
            f"channel {packet.dest_channel} is not bound to a client"
        )
    # the packet's routing must match the channel REGISTRY, not the
    # relayer's claims: the proven commitment key is scoped to the source
    # channel only, so without this check one committed packet could be
    # delivered on every destination channel bound to the same client
    # (cross-channel replay; ibc-go checks Counterparty.ChannelId in
    # RecvPacket the same way)
    ch = stack.channels.channels.get(packet.dest_channel)
    if ch is None or ch.state != "OPEN":
        raise ClientError(f"channel {packet.dest_channel} is not open")
    if (
        ch.counterparty_channel != packet.source_channel
        or ch.counterparty_port != packet.source_port
        or ch.port != packet.dest_port
    ):
        raise ClientError(
            "packet routing does not match the channel's counterparty"
        )
    if stack.channels.has_receipt(packet.dest_channel, seq):
        raise ClientError(f"packet {packet.dest_channel}#{seq} already received")
    # ICS-4 timeout: once THIS chain's height passes the packet's
    # timeout, receiving is deterministically refused — which is what
    # makes the source side's absence-proof refund safe (the packet can
    # never be delivered after the proven height)
    if packet.timeout_height and stack.app is not None:
        if stack.app.store.last_height >= packet.timeout_height:
            raise ClientError(
                f"packet timed out at height {packet.timeout_height}"
            )
    from celestia_tpu.state.modules.ibc_client import packet_commitment

    client.verify_membership(
        proof_height,
        commitment_key(packet.source_channel, seq),
        packet_commitment(packet.data, packet.timeout_height),
        proof,
    )
    stack.channels.write_receipt(packet.dest_channel, seq)
    ack = stack.on_recv_packet(packet)
    stack.channels.write_ack(packet.dest_channel, seq, ack)
    return ack


def ack_packet_verified(
    stack: IBCStack,
    packet: Packet,
    seq: int,
    ack: Acknowledgement,
    proof: dict,
    proof_height: int,
) -> None:
    """Proof-gated acknowledgement (ibc-go core AcknowledgePacket): the
    claimed ack must be proven written on the counterparty before the
    send side acts on it — a lying relayer cannot trigger a refund (error
    ack) or suppress one (forged success)."""
    from celestia_tpu.state.modules.ibc_client import (
        ClientError,
        ack_bytes,
        ack_key,
    )

    client = stack.connections.client_for_channel(packet.source_channel)
    if client is None:
        raise ClientError(
            f"channel {packet.source_channel} is not bound to a client"
        )
    # pin the ack's location to OUR channel's registered counterparty —
    # a relayer-chosen dest_channel could otherwise prove some OTHER
    # channel's success ack and suppress this packet's refund
    ch = stack.channels.channels.get(packet.source_channel)
    if ch is None:
        raise ClientError(f"unknown channel {packet.source_channel}")
    if (
        ch.counterparty_channel != packet.dest_channel
        or ch.counterparty_port != packet.dest_port
        or ch.port != packet.source_port
    ):
        raise ClientError(
            "ack routing does not match the channel's counterparty"
        )
    client.verify_membership(
        proof_height,
        ack_key(packet.dest_channel, seq),
        hashlib.sha256(ack_bytes(ack)).digest(),
        proof,
    )
    stack.app_module_for(packet).on_acknowledgement(packet, seq, ack)


def timeout_packet_verified(
    stack: IBCStack,
    packet: Packet,
    seq: int,
    absence_proof: dict,
    proof_height: int,
) -> None:
    """Proof-gated timeout (ibc-go core TimeoutPacket): refund only with
    an ABSENCE proof that the destination never wrote a receive receipt
    for this packet, at a proven height at or past the packet's timeout.
    Because the destination deterministically refuses receives once its
    height passes timeout_height (recv_packet_verified), a packet proven
    unreceived at such a height can never be delivered later — the refund
    cannot double-spend."""
    from celestia_tpu.state.modules.ibc_client import (
        ClientError,
        receipt_key,
    )

    if not packet.timeout_height:
        raise ClientError("packet has no timeout; it cannot be timed out")
    client = stack.connections.client_for_channel(packet.source_channel)
    if client is None:
        raise ClientError(
            f"channel {packet.source_channel} is not bound to a client"
        )
    ch = stack.channels.channels.get(packet.source_channel)
    if ch is None:
        raise ClientError(f"unknown channel {packet.source_channel}")
    if (
        ch.counterparty_channel != packet.dest_channel
        or ch.counterparty_port != packet.dest_port
        or ch.port != packet.source_port
    ):
        raise ClientError(
            "timeout routing does not match the channel's counterparty"
        )
    # the proven height must itself be past the timeout: consensus state
    # at H proves the destination's state as of H-1
    if proof_height - 1 < packet.timeout_height:
        raise ClientError(
            f"proof height {proof_height} does not show the timeout "
            f"({packet.timeout_height}) elapsed"
        )
    client.verify_non_membership(
        proof_height,
        receipt_key(packet.dest_channel, seq),
        absence_proof,
    )
    stack.app_module_for(packet).on_timeout_packet(packet, seq)


class SecureRelayer:
    """An UNTRUSTED relayer between two App-backed chains: it moves
    (header, certificate) pairs to update clients and (packet, proof)
    pairs to deliver — every byte it carries is verified by the receiving
    chain.  chain handles must expose .app (the App) and .header_and_cert
    (height -> (header_fields, precommit wires)); see
    tests/test_ibc_light_client.py for the BFT-network-backed harness."""

    def __init__(self, a, b):
        self.a, self.b = a, b

    def _other(self, chain):
        return self.b if chain is self.a else self.a

    def update_client(self, dst_chain, src_chain, height: int) -> int:
        header, cert = src_chain.header_and_cert(height)
        client = dst_chain.client_of_counterparty
        return client.update(header, cert)

    def relay(self, src_chain, packet: Packet, seq: int) -> Acknowledgement:
        """Full verified lifecycle: commit the send, prove the commitment
        to the destination, receive, commit the ack, prove it back.

        Height arithmetic (Tendermint convention): state written before
        block H is committed in app_hash(H); the header at H+1 carries
        prev_app_hash = app_hash(H); so a proof generated at H verifies
        against the destination client's consensus state at H+1."""
        from celestia_tpu.state.modules.ibc_client import ack_key, commitment_key

        dst_chain = self._other(src_chain)
        # 1. commit the send, then the header that proves it
        src_chain.commit_block()  # height H: includes the commitment
        src_chain.commit_block()  # height H+1: header proves app_hash(H)
        h = src_chain.app.store.last_height - 1
        self.update_client(dst_chain, src_chain, h + 1)
        proof = src_chain.app.store.prove(
            "ibc", commitment_key(packet.source_channel, seq), h
        )
        ack = recv_packet_verified(dst_chain.stack, packet, seq, proof, h + 1)
        # 2. destination commits the ack, then proves it back
        dst_chain.commit_block()
        dst_chain.commit_block()
        d = dst_chain.app.store.last_height - 1
        self.update_client(src_chain, dst_chain, d + 1)
        ack_proof = dst_chain.app.store.prove(
            "ibc", ack_key(packet.dest_channel, seq), d
        )
        ack_packet_verified(src_chain.stack, packet, seq, ack, ack_proof, d + 1)
        return ack

    def timeout(self, src_chain, packet: Packet, seq: int) -> None:
        """Trustless timeout: wait for the destination to provably pass
        the packet's timeout height, then refund against an ABSENCE proof
        of the receive receipt."""
        from celestia_tpu.state.modules.ibc_client import receipt_key

        dst_chain = self._other(src_chain)
        while dst_chain.app.store.last_height < packet.timeout_height:
            dst_chain.commit_block()
        dst_chain.commit_block()  # header proving the post-timeout state
        d = dst_chain.app.store.last_height - 1
        self.update_client(src_chain, dst_chain, d + 1)
        proof = dst_chain.app.store.prove(
            "ibc", receipt_key(packet.dest_channel, seq), d
        )
        timeout_packet_verified(src_chain.stack, packet, seq, proof, d + 1)
