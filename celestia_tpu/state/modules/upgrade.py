"""x/upgrade: single-binary coordinated upgrades via validator signalling.

Parity with /root/reference/x/upgrade/: validators MsgSignalVersion for the
current or next app version (keeper.go:60), MsgTryUpgrade tallies signalled
power (keeper.go:87, TallyVotingPower :137) and schedules the upgrade once
>= 5/6 of bonded power signalled; the app's EndBlocker consumes
ShouldUpgrade to bump the app version and run migrations
(app/app.go:675-708, ADR-018).  Signals reset on upgrade.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from celestia_tpu.state.staking import StakingKeeper
from celestia_tpu.state.store import KVStore

_SIGNAL_PREFIX = b"signal/"
_PENDING_KEY = b"pending_upgrade"

# quorum: 5/6 of total bonded power (keeper.go threshold)
QUORUM_NUM = 5
QUORUM_DEN = 6


class UpgradeKeeper:
    def __init__(self, store: KVStore, staking: StakingKeeper):
        self.store = store
        self.staking = staking

    # --- signalling -------------------------------------------------------

    def signal_version(self, validator: bytes, version: int, current_version: int) -> None:
        if self.staking.validator(validator) is None:
            raise ValueError(f"unknown validator {validator.hex()}")
        if version not in (current_version, current_version + 1):
            raise ValueError(
                f"can only signal the current ({current_version}) or next "
                f"({current_version + 1}) version, got {version}"
            )
        self.store.set(_SIGNAL_PREFIX + validator, version.to_bytes(8, "big"))

    def signals(self) -> Dict[bytes, int]:
        return {
            k[len(_SIGNAL_PREFIX):]: int.from_bytes(v, "big")
            for k, v in self.store.iterate(_SIGNAL_PREFIX)
        }

    def tally_voting_power(self, version: int) -> Tuple[int, int]:
        """(power signalled for version, total bonded power)."""
        powers = self.staking.powers_snapshot()
        signalled = sum(
            powers.get(val, 0)
            for val, v in self.signals().items()
            if v == version
        )
        return signalled, self.staking.total_power()

    def try_upgrade(self, current_version: int) -> bool:
        """Tally for current+1; if quorum met, schedule the upgrade
        (consumed by the app's EndBlocker)."""
        target = current_version + 1
        signalled, total = self.tally_voting_power(target)
        if total == 0:
            return False
        if signalled * QUORUM_DEN >= QUORUM_NUM * total:
            self.store.set(_PENDING_KEY, target.to_bytes(8, "big"))
            return True
        return False

    # --- EndBlocker consumption -------------------------------------------

    def should_upgrade(self) -> Optional[int]:
        raw = self.store.get(_PENDING_KEY)
        return int.from_bytes(raw, "big") if raw else None

    def consume_upgrade(self) -> None:
        """Clear pending upgrade + all signals (post-migration reset)."""
        self.store.delete(_PENDING_KEY)
        for val in list(self.signals()):
            self.store.delete(_SIGNAL_PREFIX + val)
