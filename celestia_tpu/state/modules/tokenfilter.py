"""x/tokenfilter: IBC middleware rejecting inbound non-native tokens.

Parity with /root/reference/x/tokenfilter/ibc_middleware.go:38-80: Celestia
only accepts transfer packets whose token is native TIA returning home
(denom prefixed with this chain's port/channel, per ICS-20 denom-trace
rules); any foreign token is rejected with an error acknowledgement instead
of being minted as a voucher.

The IBC transport itself is out of scope for this node (no IBC channels are
wired yet); the middleware is a pure function over ICS-20 packet data so the
policy is testable and ready to mount on a future transfer stack.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

NATIVE_DENOM = "utia"


@dataclass(frozen=True)
class FungibleTokenPacketData:
    """ICS-20 packet payload (memo carries packet-forward instructions)."""

    denom: str
    amount: str
    sender: str
    receiver: str
    memo: str = ""

    @classmethod
    def from_json(cls, raw: bytes) -> "FungibleTokenPacketData":
        d = json.loads(raw)
        return cls(
            d["denom"], d["amount"], d["sender"], d["receiver"],
            d.get("memo", ""),
        )

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "denom": self.denom,
                "amount": self.amount,
                "sender": self.sender,
                "receiver": self.receiver,
                "memo": self.memo,
            },
            sort_keys=True,
        ).encode()


@dataclass(frozen=True)
class Packet:
    source_port: str
    source_channel: str
    dest_port: str
    dest_channel: str
    data: bytes
    # ICS-4 timeout: the packet is undeliverable once the DESTINATION
    # chain's height exceeds this (0 = no timeout).  Covered by the
    # packet commitment, so a relayer cannot alter it.
    timeout_height: int = 0


@dataclass(frozen=True)
class Acknowledgement:
    success: bool
    error: str = ""


def on_recv_packet(packet: Packet) -> Acknowledgement:
    """tokenFilterMiddleware.OnRecvPacket parity: accept only returning
    native tokens.

    In ICS-20, a token that originated HERE and is coming back carries a
    denom prefixed with the packet's source port/channel (the counterparty
    held it as a voucher).  Anything else is a foreign token -> reject.
    """
    try:
        data = FungibleTokenPacketData.from_json(packet.data)
    except (ValueError, KeyError):
        return Acknowledgement(False, "cannot unmarshal ICS-20 packet data")
    prefix = f"{packet.source_port}/{packet.source_channel}/"
    if data.denom.startswith(prefix):
        # strip one hop; if what remains is the native denom (possibly with
        # no further hops), this is TIA returning home
        remainder = data.denom[len(prefix):]
        if remainder == NATIVE_DENOM:
            return Acknowledgement(True)
        # still a returning voucher of something we minted? only native is held
        return Acknowledgement(
            False, f"only native {NATIVE_DENOM} may return; got {remainder!r}"
        )
    return Acknowledgement(
        False,
        f"token {data.denom!r} originating elsewhere is not accepted by this chain",
    )
