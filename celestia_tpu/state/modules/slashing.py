"""x/slashing equivalent: liveness tracking, downtime jailing, and the
double-sign slash entry point consumed by x/evidence.

Parity role: the cosmos-sdk slashing keeper the reference wires at
/root/reference/app/app.go:192,307-310 (SlashingKeeper + staking hooks).
Per-validator signing info tracks a sliding missed-block window; crossing
the liveness threshold slashes a fraction of stake and jails for a
duration.  Equivocation (from x/evidence) slashes harder and tombstones —
the validator can never rejoin.

Integer-only params (ppm fractions, ns durations) keep every validator's
arithmetic bit-identical — the same determinism rule as the rest of the
state machine.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.staking import StakingKeeper
from celestia_tpu.state.store import KVStore

# SDK-default-shaped params, window scaled for 15s blocks
SIGNED_BLOCKS_WINDOW = 100
MIN_SIGNED_PER_WINDOW_PPM = 500_000  # 50%
DOWNTIME_JAIL_DURATION_NS = 600 * 10**9  # 10 minutes
SLASH_FRACTION_DOWNTIME_PPM = 10_000  # 1%
SLASH_FRACTION_DOUBLE_SIGN_PPM = 50_000  # 5%

_INFO_PREFIX = b"si/"
_BITMAP_PREFIX = b"bm/"


class SlashingError(ValueError):
    pass


@dataclass
class SigningInfo:
    start_height: int = 0
    index_offset: int = 0
    missed_blocks: int = 0

    def marshal(self) -> bytes:
        return bytes(
            _varint(self.start_height)
            + _varint(self.index_offset)
            + _varint(self.missed_blocks)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "SigningInfo":
        sh, pos = _read_varint(raw, 0)
        io, pos = _read_varint(raw, pos)
        mb, pos = _read_varint(raw, pos)
        return cls(sh, io, mb)


class SlashingKeeper:
    def __init__(
        self,
        store: KVStore,
        staking: StakingKeeper,
        window: int = SIGNED_BLOCKS_WINDOW,
    ):
        self.store = store
        self.staking = staking
        self.window = window

    # -- signing info ---------------------------------------------------

    def signing_info(self, operator: bytes) -> Optional[SigningInfo]:
        raw = self.store.get(_INFO_PREFIX + operator)
        return SigningInfo.unmarshal(raw) if raw is not None else None

    def _set_info(self, operator: bytes, info: SigningInfo) -> None:
        self.store.set(_INFO_PREFIX + operator, info.marshal())

    def _bitmap_get(self, operator: bytes, index: int) -> bool:
        return self.store.get(
            _BITMAP_PREFIX + operator + index.to_bytes(4, "big")
        ) is not None

    def _bitmap_set(self, operator: bytes, index: int, missed: bool) -> None:
        key = _BITMAP_PREFIX + operator + index.to_bytes(4, "big")
        if missed:
            self.store.set(key, b"\x01")
        else:
            self.store.delete(key)

    def _reset_window(self, operator: bytes, info: SigningInfo) -> None:
        for i in range(self.window):
            self._bitmap_set(operator, i, False)
        info.missed_blocks = 0
        info.index_offset = 0

    # -- liveness -------------------------------------------------------

    def handle_validator_signature(
        self, operator: bytes, signed: bool, height: int, now_ns: int
    ) -> Optional[int]:
        """Record one block's vote for a bonded validator; slash + jail on
        crossing the downtime threshold (SDK HandleValidatorSignature).
        Returns the slashed amount, or None if no slashing happened."""
        v = self.staking.validator(operator)
        if v is None or v.jailed:
            return None
        info = self.signing_info(operator)
        if info is None:
            info = SigningInfo(start_height=height)
        idx = info.index_offset % self.window
        info.index_offset += 1
        previously_missed = self._bitmap_get(operator, idx)
        if not signed and not previously_missed:
            info.missed_blocks += 1
            self._bitmap_set(operator, idx, True)
        elif signed and previously_missed:
            info.missed_blocks -= 1
            self._bitmap_set(operator, idx, False)

        max_missed = self.window - self.window * MIN_SIGNED_PER_WINDOW_PPM // 1_000_000
        slashed = None
        # only enforce once the validator has been around a full window
        if (
            height >= info.start_height + self.window
            and info.missed_blocks > max_missed
        ):
            slashed = self.staking.slash(operator, SLASH_FRACTION_DOWNTIME_PPM)
            self.staking.jail(operator, now_ns + DOWNTIME_JAIL_DURATION_NS)
            # reset the window so the validator starts clean after unjail
            self._reset_window(operator, info)
            info.start_height = height
        self._set_info(operator, info)
        return slashed

    def begin_blocker(
        self,
        votes: List[Tuple[bytes, bool]],
        height: int,
        now_ns: int,
    ) -> Dict[bytes, int]:
        """Process the previous commit's votes (SDK slashing BeginBlocker)."""
        slashes: Dict[bytes, int] = {}
        for operator, signed in votes:
            s = self.handle_validator_signature(operator, signed, height, now_ns)
            if s is not None:
                slashes[operator] = s
        return slashes

    # -- infractions ----------------------------------------------------

    def handle_equivocation(self, operator: bytes) -> int:
        """Double-sign: slash hard and tombstone (never unjailable) — the
        x/evidence -> slashing path."""
        v = self.staking.validator(operator)
        if v is None:
            raise SlashingError(f"unknown validator {operator.hex()}")
        if v.tombstoned:
            raise SlashingError("validator already tombstoned")
        slashed = self.staking.slash(operator, SLASH_FRACTION_DOUBLE_SIGN_PPM)
        self.staking.tombstone(operator)
        return slashed

    def unjail(self, operator: bytes, now_ns: int) -> None:
        """MsgUnjail: validator rejoins after the jail duration (never after
        a tombstone)."""
        self.staking.unjail(operator, now_ns)
