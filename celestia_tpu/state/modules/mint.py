"""x/mint: time-based (not block-based) inflation.

Parity with /root/reference/x/mint/: BeginBlocker (abci.go:14-20),
CalculateInflationRate (types/minter.go:43-52, 8% initial, -10%/yr decay,
1.5% floor), CalculateBlockProvision (types/minter.go:56-65, proportional to
wall-clock elapsed since the previous block), constants
(types/constants.go).

All arithmetic is integer fixed-point (ppm for rates, nanoseconds for time)
so every validator computes identical provisions — the decimal-determinism
requirement the reference gets from sdk.Dec.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.bank import FEE_COLLECTOR, BankKeeper
from celestia_tpu.state.store import KVStore

INITIAL_INFLATION_PPM = 80_000  # 8.00%
DISINFLATION_RATE_PCT = 10  # -10% per year
TARGET_INFLATION_PPM = 15_000  # 1.50% floor
NANOSECONDS_PER_YEAR = 365_2425 * 24 * 60 * 60 * 10**9 // 10_000  # 365.2425 d

_STATE_KEY = b"minter"


def inflation_rate_ppm(years_since_genesis: int) -> int:
    """max(8% * 0.9^years, 1.5%) in parts-per-million (minter.go:43-52)."""
    if years_since_genesis < 0:
        years_since_genesis = 0
    num = INITIAL_INFLATION_PPM * (100 - DISINFLATION_RATE_PCT) ** years_since_genesis
    den = 100**years_since_genesis
    rate = num // den
    return max(rate, TARGET_INFLATION_PPM)


@dataclass
class MinterState:
    genesis_time_ns: int
    previous_block_time_ns: int
    inflation_ppm: int = INITIAL_INFLATION_PPM
    annual_provisions: int = 0  # utia/year

    def marshal(self) -> bytes:
        out = bytearray()
        for v in (
            self.genesis_time_ns,
            self.previous_block_time_ns,
            self.inflation_ppm,
            self.annual_provisions,
        ):
            out += _varint(v)
        return bytes(out)

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MinterState":
        vals = []
        pos = 0
        for _ in range(4):
            v, pos = _read_varint(raw, pos)
            vals.append(v)
        return cls(*vals)


class MintKeeper:
    def __init__(self, store: KVStore, bank: BankKeeper):
        self.store = store
        self.bank = bank

    def state(self) -> Optional[MinterState]:
        raw = self.store.get(_STATE_KEY)
        return MinterState.unmarshal(raw) if raw else None

    def set_state(self, s: MinterState) -> None:
        self.store.set(_STATE_KEY, s.marshal())

    def init_genesis(self, genesis_time_ns: int) -> None:
        self.set_state(MinterState(genesis_time_ns, genesis_time_ns))

    def begin_blocker(self, block_time_ns: int) -> int:
        """Mint the block provision to the fee collector; returns utia minted
        (x/mint/abci.go:14-20)."""
        s = self.state()
        if s is None:
            raise RuntimeError("mint module not initialized at genesis")
        years = (block_time_ns - s.genesis_time_ns) // NANOSECONDS_PER_YEAR
        s.inflation_ppm = inflation_rate_ppm(years)
        s.annual_provisions = self.bank.supply() * s.inflation_ppm // 1_000_000
        elapsed_ns = max(block_time_ns - s.previous_block_time_ns, 0)
        provision = s.annual_provisions * elapsed_ns // NANOSECONDS_PER_YEAR
        if provision > 0:
            self.bank.mint(FEE_COLLECTOR, provision)
        s.previous_block_time_ns = block_time_ns
        self.set_state(s)
        return provision
