"""x/distribution equivalent: fee + inflation distribution to validators,
delegators, and the community pool.

Parity role: the cosmos-sdk distribution keeper the reference wires at
/root/reference/app/app.go:303-306 (DistrKeeper: community tax, proposer
reward, per-validator commission, F1 delegator rewards, withdraw msgs).

Design: the SDK's F1 fee-distribution scheme reduced to one cumulative
"reward per staked token" accumulator per validator (scaled by 1e18 for
integer precision).  Each delegation stores the accumulator value at its
last settlement; pending rewards = stake x (accum_now - accum_then).  A
before-delegation-modified staking hook settles rewards whenever stake
changes, which is exactly the invariant F1's period mechanism protects.
All arithmetic is integer — determinism across validators is a consensus
requirement.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.bank import BankKeeper, FEE_COLLECTOR, module_address
from celestia_tpu.state.staking import StakingKeeper
from celestia_tpu.state.store import KVStore

DISTRIBUTION_MODULE = module_address("distribution")

SCALE = 10**18  # accumulator fixed-point scale

# distribution params (SDK defaults, integer ppm)
COMMUNITY_TAX_PPM = 20_000  # 2%
BASE_PROPOSER_REWARD_PPM = 10_000  # 1%
BONUS_PROPOSER_REWARD_PPM = 40_000  # up to 4%, scaled by precommit power

_ACCUM_PREFIX = b"acc/"  # val -> cumulative reward-per-token (scaled)
_COMMISSION_PREFIX = b"com/"  # val -> accrued commission (utia)
_REF_PREFIX = b"ref/"  # delegator+val -> (stake, accum at settlement)
_WITHDRAW_ADDR_PREFIX = b"wa/"  # delegator -> withdraw address
_COMMUNITY_POOL_KEY = b"community_pool"
_DUST_KEY = b"dust"  # rounding residue retained by the module account


class DistributionError(ValueError):
    pass


class DistributionKeeper:
    def __init__(self, store: KVStore, bank: BankKeeper, staking: StakingKeeper):
        self.store = store
        self.bank = bank
        self.staking = staking

    def register_hooks(self) -> None:
        """Subscribe to staking: settle rewards before a stake change (the
        stored reference stake is what accrued), re-anchor at the new stake
        after (zero-delta settle; F1 period rollover)."""
        self.staking.hooks_before_delegation_modified.append(self._settle)
        self.staking.hooks_after_delegation_modified.append(self._settle)

    # -- small int helpers ---------------------------------------------

    def _get_int(self, key: bytes) -> int:
        raw = self.store.get(key)
        return int.from_bytes(raw, "big") if raw else 0

    def _set_int(self, key: bytes, value: int) -> None:
        if value:
            self.store.set(key, value.to_bytes(32, "big"))
        else:
            self.store.delete(key)

    # -- public read surface -------------------------------------------

    def community_pool(self) -> int:
        return self._get_int(_COMMUNITY_POOL_KEY)

    def commission(self, operator: bytes) -> int:
        return self._get_int(_COMMISSION_PREFIX + operator)

    def accumulator(self, operator: bytes) -> int:
        return self._get_int(_ACCUM_PREFIX + operator)

    def withdraw_address(self, delegator: bytes) -> bytes:
        raw = self.store.get(_WITHDRAW_ADDR_PREFIX + delegator)
        return raw if raw else delegator

    def set_withdraw_address(self, delegator: bytes, addr: bytes) -> None:
        self.store.set(_WITHDRAW_ADDR_PREFIX + delegator, addr)

    # -- delegation reference points -----------------------------------

    def _get_ref(self, delegator: bytes, operator: bytes) -> Tuple[int, int]:
        raw = self.store.get(_REF_PREFIX + delegator + operator)
        if raw is None:
            return 0, 0
        stake, pos = _read_varint(raw, 0)
        accum, pos = _read_varint(raw, pos)
        return stake, accum

    def _set_ref(
        self, delegator: bytes, operator: bytes, stake: int, accum: int
    ) -> None:
        if stake == 0 and accum == 0:
            self.store.delete(_REF_PREFIX + delegator + operator)
        else:
            self.store.set(
                _REF_PREFIX + delegator + operator,
                bytes(_varint(stake) + _varint(accum)),
            )

    def pending_rewards(self, delegator: bytes, operator: bytes) -> int:
        """Unsettled rewards since the last reference point, PLUS rewards
        for stake the keeper hasn't seen settle yet (a delegation made
        before distribution was wired starts at accum of first sight)."""
        stake, accum_then = self._get_ref(delegator, operator)
        accum_now = self.accumulator(operator)
        return stake * (accum_now - accum_then) // SCALE

    def _settle(self, delegator: bytes, operator: bytes) -> int:
        """Pay rewards accrued on the STORED reference stake, then anchor
        the reference point at the actual current stake."""
        reward = self.pending_rewards(delegator, operator)
        if reward:
            self.bank.send(
                DISTRIBUTION_MODULE, self.withdraw_address(delegator), reward
            )
        current_stake = self.staking.delegation(delegator, operator)
        self._set_ref(delegator, operator, current_stake, self.accumulator(operator))
        return reward

    # -- BeginBlocker: allocate the previous block's fees ---------------

    def allocate_tokens(
        self,
        proposer: Optional[bytes],
        votes: Optional[List[Tuple[bytes, bool]]] = None,
    ) -> Dict[str, int]:
        """Drain the fee collector (tx fees + that block's mint provision)
        into: community pool (2%), proposer reward (1% + up to 4% by signed
        power), and power-proportional validator rewards — the SDK
        AllocateTokens shape.  Votes are (operator, signed) pairs from the
        previous block's commit; None means every bonded validator signed."""
        fees = self.bank.balance(FEE_COLLECTOR)
        if fees == 0:
            return {"fees": 0}
        self.bank.send(FEE_COLLECTOR, DISTRIBUTION_MODULE, fees)

        bonded = {v.operator: v for v in self.staking.bonded_validators()}
        if votes is None:
            votes = [(op, True) for op in bonded]
        signed_power = sum(
            bonded[op].power for op, ok in votes if ok and op in bonded
        )
        total_power = sum(v.power for v in bonded.values())
        if total_power == 0 or signed_power == 0:
            # no validators to pay: everything goes to the community pool
            self._set_int(_COMMUNITY_POOL_KEY, self.community_pool() + fees)
            return {"fees": fees, "community": fees}

        community = fees * COMMUNITY_TAX_PPM // 1_000_000
        remaining = fees - community

        proposer_reward = 0
        if proposer is not None and proposer in bonded:
            # base 1% + bonus 4% x (signed power / total power)
            ppm = (
                BASE_PROPOSER_REWARD_PPM
                + BONUS_PROPOSER_REWARD_PPM * signed_power // total_power
            )
            proposer_reward = fees * ppm // 1_000_000
            self._credit_validator(bonded[proposer], proposer_reward)
            remaining -= proposer_reward

        # the rest splits over validators that signed, by power
        distributed = 0
        for op, ok in votes:
            if not ok or op not in bonded:
                continue
            share = remaining * bonded[op].power // signed_power
            self._credit_validator(bonded[op], share)
            distributed += share
        # integer-division dust accrues to the community pool
        community += remaining - distributed
        self._set_int(_COMMUNITY_POOL_KEY, self.community_pool() + community)
        return {
            "fees": fees,
            "community": community,
            "proposer": proposer_reward,
            "distributed": distributed,
        }

    def _credit_validator(self, validator, amount: int) -> None:
        """Split one validator's allocation into commission + delegator
        rewards; fold the delegator part into the F1 accumulator."""
        if amount == 0:
            return
        commission = amount * validator.commission_ppm // 1_000_000
        to_delegators = amount - commission
        op = validator.operator
        self._set_int(
            _COMMISSION_PREFIX + op, self.commission(op) + commission
        )
        if validator.tokens > 0 and to_delegators > 0:
            delta = to_delegators * SCALE // validator.tokens
            self._set_int(_ACCUM_PREFIX + op, self.accumulator(op) + delta)
            # per-token rounding dust stays in the module account
            dust = to_delegators - delta * validator.tokens // SCALE
            self._set_int(_DUST_KEY, self._get_int(_DUST_KEY) + dust)
        else:
            self._set_int(
                _COMMISSION_PREFIX + op, self.commission(op) + to_delegators
            )

    # -- msg handlers ---------------------------------------------------

    def withdraw_delegator_reward(self, delegator: bytes, operator: bytes) -> int:
        if self.staking.validator(operator) is None:
            raise DistributionError(f"unknown validator {operator.hex()}")
        return self._settle(delegator, operator)

    def withdraw_validator_commission(self, operator: bytes) -> int:
        amount = self.commission(operator)
        if amount == 0:
            raise DistributionError("no commission to withdraw")
        self._set_int(_COMMISSION_PREFIX + operator, 0)
        self.bank.send(
            DISTRIBUTION_MODULE, self.withdraw_address(operator), amount
        )
        return amount

    def fund_community_pool(self, from_addr: bytes, amount: int) -> None:
        self.bank.send(from_addr, DISTRIBUTION_MODULE, amount)
        self._set_int(_COMMUNITY_POOL_KEY, self.community_pool() + amount)

    def spend_community_pool(self, to_addr: bytes, amount: int) -> None:
        """Gov-gated community pool spend (CommunityPoolSpendProposal)."""
        pool = self.community_pool()
        if amount > pool:
            raise DistributionError(
                f"community pool has {pool}utia < spend {amount}utia"
            )
        self._set_int(_COMMUNITY_POOL_KEY, pool - amount)
        self.bank.send(DISTRIBUTION_MODULE, to_addr, amount)
