"""x/evidence equivalent: equivocation (double-sign) evidence handling.

Parity role: the cosmos-sdk evidence keeper the reference wires at
/root/reference/app/app.go:200,328-332 (EvidenceKeeper routing equivocation
to the slashing keeper).  Evidence too old to act on is ignored (max-age
window, both height- and time-bounded like CometBFT's consensus params);
fresh evidence slashes + tombstones through x/slashing and is recorded so
a replay cannot double-slash.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.modules.slashing import SlashingKeeper
from celestia_tpu.state.store import KVStore

MAX_AGE_NUM_BLOCKS = 100_000
MAX_AGE_DURATION_NS = 14 * 24 * 3600 * 10**9  # two weeks

_EVIDENCE_PREFIX = b"ev/"


class EvidenceError(ValueError):
    pass


def vote_sign_bytes(chain_id: str, height: int, block_hash: bytes) -> bytes:
    """Canonical consensus-vote digest a validator signs (one per height;
    two different block hashes at one height = equivocation)."""
    return hashlib.sha256(
        b"consensus-vote" + chain_id.encode() + _varint(height) + block_hash
    ).digest()


@dataclass(frozen=True)
class Equivocation:
    """Double-sign evidence: one validator, two CONFLICTING SIGNED votes at
    one height.  Unlike the SDK (where comet verifies evidence before it
    reaches the app), the msg-based submission path here is open to anyone,
    so the evidence must prove itself: both votes must verify under the
    validator's registered pubkey and commit to different block hashes."""

    validator: bytes
    height: int
    time_ns: int
    block_hash_a: bytes = b""
    sig_a: bytes = b""
    block_hash_b: bytes = b""
    sig_b: bytes = b""

    def hash(self) -> bytes:
        return hashlib.sha256(
            b"equivocation" + self.validator + _varint(self.height)
            + _varint(self.time_ns)
        ).digest()

    def verify(self, chain_id: str, pubkey: bytes) -> None:
        """Raise EvidenceError unless this is a provable double-sign."""
        from celestia_tpu.utils.secp256k1 import PublicKey

        if self.block_hash_a == self.block_hash_b:
            raise EvidenceError("votes commit to the same block: no conflict")
        if not pubkey:
            raise EvidenceError("validator has no registered pubkey")
        try:
            pk = PublicKey.from_compressed(pubkey)
        except ValueError as e:
            raise EvidenceError(f"bad validator pubkey: {e}") from e
        for bh, sig, name in (
            (self.block_hash_a, self.sig_a, "a"),
            (self.block_hash_b, self.sig_b, "b"),
        ):
            if not pk.verify(vote_sign_bytes(chain_id, self.height, bh), sig):
                raise EvidenceError(f"vote {name} signature does not verify")

    def marshal(self) -> bytes:
        out = bytearray()
        out += self.validator
        out += _varint(self.height)
        out += _varint(self.time_ns)
        for b in (self.block_hash_a, self.sig_a, self.block_hash_b, self.sig_b):
            out += _varint(len(b))
            out += b
        return bytes(out)

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Equivocation":
        val = raw[:20]
        h, pos = _read_varint(raw, 20)
        t, pos = _read_varint(raw, pos)
        fields = []
        for _ in range(4):
            n, pos = _read_varint(raw, pos)
            fields.append(raw[pos : pos + n])
            pos += n
        return cls(val, h, t, *fields)


class EvidenceKeeper:
    def __init__(self, store: KVStore, slashing: SlashingKeeper):
        self.store = store
        self.slashing = slashing

    def get(self, evidence_hash: bytes) -> Optional[Equivocation]:
        raw = self.store.get(_EVIDENCE_PREFIX + evidence_hash)
        return Equivocation.unmarshal(raw) if raw is not None else None

    def all_evidence(self) -> List[Equivocation]:
        return [
            Equivocation.unmarshal(v)
            for _, v in self.store.iterate(_EVIDENCE_PREFIX)
        ]

    def submit(
        self,
        ev: Equivocation,
        current_height: int,
        now_ns: int,
        chain_id: str = "",
        pubkey: bytes = b"",
    ) -> int:
        """Validate, record, and act on equivocation evidence.  Returns the
        slashed amount (SDK HandleEquivocationEvidence).  When chain_id is
        provided the evidence must PROVE the double-sign (two conflicting
        votes verifying under the validator's pubkey) — fabricated evidence
        must never slash."""
        if chain_id:
            ev.verify(chain_id, pubkey)
        if ev.height <= 0 or ev.height > current_height:
            raise EvidenceError(
                f"evidence height {ev.height} outside (0, {current_height}]"
            )
        age_blocks = current_height - ev.height
        age_ns = now_ns - ev.time_ns
        # expire only when BOTH bounds are exceeded (CometBFT's rule).
        # ev.time_ns is submitter-supplied and not signature-covered, so it
        # must never be the SOLE gate in either direction: the height bound
        # (consensus-verified) always has the final say.
        if age_blocks > MAX_AGE_NUM_BLOCKS and age_ns > MAX_AGE_DURATION_NS:
            raise EvidenceError(
                f"evidence too old: {age_blocks} blocks / {age_ns}ns past max age"
            )
        if self.get(ev.hash()) is not None:
            raise EvidenceError("evidence already submitted")
        slashed = self.slashing.handle_equivocation(ev.validator)
        self.store.set(_EVIDENCE_PREFIX + ev.hash(), ev.marshal())
        return slashed
