"""x/blob: PayForBlobs validation, gas metering, params.

Parity with /root/reference/x/blob/: MsgPayForBlobs ValidateBasic
(types/payforblob.go:58-146), GasToConsume (:155-163), ValidateBlobTx
(types/blob_tx.go:37-110, incl. the commitment recompute at :100), keeper
PayForBlobs gas consumption (keeper/keeper.go:42-57), params
GasPerBlobByte=8 / GovMaxSquareSize=64.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from celestia_tpu.appconsts import (
    DEFAULT_GAS_PER_BLOB_BYTE,
    DEFAULT_GOV_MAX_SQUARE_SIZE,
    SHARE_SIZE,
    SUPPORTED_SHARE_VERSIONS,
)
from celestia_tpu.da.blob import BlobTx
from celestia_tpu.da.inclusion import create_commitment
from celestia_tpu.da.namespace import Namespace
from celestia_tpu.da.shares import sparse_shares_needed
from celestia_tpu.state.params import ParamsKeeper
from celestia_tpu.state.tx import MsgPayForBlobs, Tx, unmarshal_tx

# Fixed gas overhead of a PFB tx beyond per-byte blob gas
# (x/blob/types/payforblob.go:21-41 envelope: 65k-75k).
PFB_GAS_FIXED_COST = 65_000
FIRST_SPARSE_SHARE_GAS = 1_000  # estimation headroom, not consensus-relevant


def gas_to_consume(blob_sizes, gas_per_blob_byte: int) -> int:
    """shares x 512 x gas_per_blob_byte (payforblob.go:155-163)."""
    total_shares = sum(sparse_shares_needed(s) for s in blob_sizes)
    return total_shares * SHARE_SIZE * gas_per_blob_byte


def estimate_gas(blob_sizes) -> int:
    """Client-side PFB gas estimate (pkg/user Signer.EstimateGas shape)."""
    return gas_to_consume(blob_sizes, DEFAULT_GAS_PER_BLOB_BYTE) + PFB_GAS_FIXED_COST


def validate_msg_pay_for_blobs(msg: MsgPayForBlobs) -> None:
    """MsgPayForBlobs.ValidateBasic parity."""
    n = len(msg.namespaces)
    if n == 0:
        raise ValueError("PFB must reference at least one blob")
    if not (n == len(msg.blob_sizes) == len(msg.share_commitments) == len(msg.share_versions)):
        raise ValueError("PFB field lengths mismatch")
    if len(msg.signer) != 20:
        raise ValueError("invalid signer address")
    for ns_raw, size, comm, ver in zip(
        msg.namespaces, msg.blob_sizes, msg.share_commitments, msg.share_versions
    ):
        Namespace(ns_raw).validate_for_blob()
        if size == 0:
            raise ValueError("blob size must be positive")
        if len(comm) != 32:
            raise ValueError("share commitment must be 32 bytes")
        if ver not in SUPPORTED_SHARE_VERSIONS:
            raise ValueError(f"unsupported share version {ver}")


def validate_blob_tx(blob_tx: BlobTx, chain_id: str) -> Tx:
    """Full BlobTx validation (types/blob_tx.go:37-110): the wrapped tx must
    contain exactly one MsgPayForBlobs whose namespaces, sizes, versions and
    share commitments match the attached blobs (commitments recomputed).

    Returns the decoded inner Tx on success.
    """
    if not blob_tx.blobs:
        raise ValueError("blob tx carries no blobs")
    tx = unmarshal_tx(blob_tx.tx)
    pfbs = [m for m in tx.msgs if isinstance(m, MsgPayForBlobs)]
    if len(pfbs) != 1 or len(tx.msgs) != 1:
        raise ValueError("blob tx must contain exactly one MsgPayForBlobs")
    msg = pfbs[0]
    validate_msg_pay_for_blobs(msg)
    if len(blob_tx.blobs) != len(msg.namespaces):
        raise ValueError("blob count does not match PFB")
    for i, b in enumerate(blob_tx.blobs):
        if b.namespace.raw != msg.namespaces[i]:
            raise ValueError(f"blob {i}: namespace mismatch with PFB")
        if len(b.data) != msg.blob_sizes[i]:
            raise ValueError(f"blob {i}: size mismatch with PFB")
        if b.share_version != msg.share_versions[i]:
            raise ValueError(f"blob {i}: share version mismatch with PFB")
        if create_commitment(b) != msg.share_commitments[i]:
            raise ValueError(f"blob {i}: share commitment mismatch")
    return tx


@dataclass
class BlobKeeper:
    params: ParamsKeeper

    def gas_per_blob_byte(self) -> int:
        return self.params.get("blob", "GasPerBlobByte", DEFAULT_GAS_PER_BLOB_BYTE)

    def gov_max_square_size(self) -> int:
        return self.params.get(
            "blob", "GovMaxSquareSize", DEFAULT_GOV_MAX_SQUARE_SIZE
        )

    def pay_for_blobs(self, msg: MsgPayForBlobs, gas_meter) -> dict:
        """Keeper.PayForBlobs: consume blob gas, emit the event
        (keeper/keeper.go:42-57)."""
        gas = gas_to_consume(msg.blob_sizes, self.gas_per_blob_byte())
        gas_meter.consume(gas, "blob payment")
        return {
            "type": "celestia.blob.v1.EventPayForBlobs",
            "signer": msg.signer.hex(),
            "blob_sizes": list(msg.blob_sizes),
            "namespaces": [ns.hex() for ns in msg.namespaces],
        }
