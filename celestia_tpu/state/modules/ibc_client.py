"""IBC light-client layer: trustless packet verification (VERDICT r2 #4).

The round-2 stack moved packets on relayer honesty.  This module adds the
trust machinery the reference gets from ibc-go core + 07-tendermint
clients (/root/reference/app/app.go:339-358):

- ``LightClient`` tracks a counterparty chain's validator set and a map
  height -> ``ConsensusState`` (state root + time).  It advances ONLY on
  a header whose BFT commit certificate verifies: >= 2/3 of the tracked
  power signed precommits over the header's block id
  (node/bft.py vote signatures), and the block id commits to
  ``prev_app_hash`` — so the certificate proves the counterparty's state
  root exactly the way a Tendermint header's AppHash is proven.
- ``Connection`` binds channels to a client (the ICS-3 role, condensed:
  the handshake's proof obligations are the membership checks below).
- Verified packet receive / acknowledgement: the relayer presents a
  merkle membership proof of the packet commitment (or ack) in the
  counterparty's "ibc" store at a proven height; the proof is checked
  against the light client's consensus state with
  state.merkle.verify_query_proof — the relayer is untrusted end to end.

Height convention (Tendermint's): the consensus state recorded at header
height H carries the state root app_hash(H-1); a proof generated against
the store committed at height G therefore verifies with the consensus
state at G+1.

Limitation (documented, round-3 scope): the tracked validator set is
fixed at client creation — valset rotation needs the next-valset hash
committed in the block id, which the payload does not carry yet.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.node.bft import (
    PRECOMMIT,
    Vote,
    block_id_of,
    vote_sign_bytes,
)
from celestia_tpu.state import merkle
from celestia_tpu.utils.secp256k1 import PublicKey


class ClientError(ValueError):
    pass


@dataclass(frozen=True)
class ConsensusState:
    root: bytes  # the counterparty app hash proofs verify against
    time_ns: int


class LightClient:
    """07-tendermint analogue over the BFT engine's vote format."""

    def __init__(
        self,
        client_id: str,
        chain_id: str,
        validators: Dict[bytes, int],  # operator address -> power
        pubkeys: Dict[bytes, bytes],  # operator address -> 33B compressed
    ):
        if not validators:
            raise ClientError("empty validator set")
        self.client_id = client_id
        self.chain_id = chain_id
        self.validators = dict(validators)
        self.pubkeys = dict(pubkeys)
        self.total_power = sum(validators.values())
        self.consensus_states: Dict[int, ConsensusState] = {}
        self.latest_height = 0
        self.frozen = False

    # -- header verification -------------------------------------------

    def update(self, header: dict, precommits: List[dict]) -> int:
        """Verify a (header, commit certificate) pair and record the
        consensus state it proves.  header = BlockPayload.header_fields()
        — the block-id preimage without txs; precommits = Vote wire
        dicts.  Returns the header height.  The caller (relayer) is
        untrusted: everything is checked against the tracked valset."""
        if self.frozen:
            raise ClientError(f"client {self.client_id} is frozen")
        height = int(header["height"])
        prev_app_hash = bytes.fromhex(header["prev_app_hash"])
        block_id = block_id_of(
            height,
            int(header["time_ns"]),
            int(header["square_size"]),
            bytes.fromhex(header["data_root"]),
            bytes.fromhex(header["proposer"]),
            bytes.fromhex(header["last_commit_digest"]),
            prev_app_hash,
        )
        votes = [Vote.from_wire(v) for v in precommits]
        if not votes:
            raise ClientError("empty certificate: below 2/3 power")
        rounds = {v.round for v in votes}
        if len(rounds) != 1:
            raise ClientError("commit certificate mixes rounds")
        seen = set()
        power = 0
        for v in votes:
            if v.vtype != PRECOMMIT or v.height != height:
                raise ClientError("certificate vote is not for this header")
            if v.block_id != block_id:
                raise ClientError("certificate vote is for a different block")
            if v.validator in seen:
                raise ClientError("duplicate validator in certificate")
            seen.add(v.validator)
            vp = self.validators.get(v.validator)
            pk = self.pubkeys.get(v.validator)
            if not vp or pk is None:
                raise ClientError("unknown validator in certificate")
            digest = vote_sign_bytes(
                self.chain_id, v.height, v.round, v.vtype, v.block_id
            )
            if not PublicKey.from_compressed(pk).verify(digest, v.signature):
                raise ClientError("certificate signature does not verify")
            power += vp
        if power * 3 < self.total_power * 2:
            raise ClientError(
                f"certificate power {power} below 2/3 of {self.total_power}"
            )
        # misbehaviour: two CERTIFIED headers at one height with different
        # roots means the counterparty valset double-signed — freeze the
        # client permanently (07-tendermint freezes the same way); a
        # relayer must never be able to pick which fork proofs verify on
        existing = self.consensus_states.get(height)
        if existing is not None and existing.root != prev_app_hash:
            self.frozen = True
            raise ClientError(
                f"misbehaviour: conflicting certified headers at height "
                f"{height}; client {self.client_id} frozen"
            )
        # Tendermint semantics: the header at H proves app_hash(H-1);
        # record it as the consensus state AT H
        self.consensus_states[height] = ConsensusState(
            root=prev_app_hash, time_ns=int(header["time_ns"])
        )
        self.latest_height = max(self.latest_height, height)
        return height

    # -- membership verification ---------------------------------------

    def verify_membership(
        self, proof_height: int, key: bytes, value: bytes, proof: dict
    ) -> None:
        """Raise ClientError unless ``proof`` shows ("ibc", key) == value
        in the counterparty state the consensus state at proof_height
        commits to.  The proof's own claimed key/value/store are checked
        AGAINST THE CALLER'S expectation — a relayer substituting a proof
        of some other key fails here."""
        cs = self.consensus_states.get(proof_height)
        if cs is None:
            raise ClientError(
                f"no consensus state at height {proof_height} "
                f"(client {self.client_id})"
            )
        if proof.get("store") != "ibc":
            raise ClientError("proof is not for the ibc store")
        if bytes.fromhex(proof["key"]) != key:
            raise ClientError("proof key does not match the packet")
        if proof["value"] is None or bytes.fromhex(proof["value"]) != value:
            raise ClientError("proof value does not match the packet")
        if not merkle.verify_query_proof(proof, cs.root):
            raise ClientError(
                "membership proof does not verify against the consensus state"
            )

    def verify_non_membership(
        self, proof_height: int, key: bytes, proof: dict
    ) -> None:
        """Absence proof (timeouts: the counterparty never wrote a
        receipt for the packet)."""
        cs = self.consensus_states.get(proof_height)
        if cs is None:
            raise ClientError(f"no consensus state at height {proof_height}")
        if proof.get("store") != "ibc":
            raise ClientError("proof is not for the ibc store")
        if bytes.fromhex(proof["key"]) != key:
            raise ClientError("proof key does not match")
        if proof["value"] is not None:
            raise ClientError("expected an absence proof")
        if not merkle.verify_query_proof(proof, cs.root):
            raise ClientError(
                "absence proof does not verify against the consensus state"
            )


@dataclass
class Connection:
    """ICS-3 condensed: a named binding of channels to a light client."""

    connection_id: str
    client: LightClient
    counterparty_connection: str = ""


class ConnectionKeeper:
    def __init__(self):
        self.clients: Dict[str, LightClient] = {}
        self.connections: Dict[str, Connection] = {}
        # channel_id -> connection_id: which client secures which channel
        self.channel_bindings: Dict[str, str] = {}

    def create_client(self, client: LightClient) -> None:
        if client.client_id in self.clients:
            raise ClientError(f"client {client.client_id} exists")
        self.clients[client.client_id] = client

    def open_connection(
        self, connection_id: str, client_id: str,
        counterparty_connection: str = "",
    ) -> Connection:
        client = self.clients.get(client_id)
        if client is None:
            raise ClientError(f"unknown client {client_id}")
        conn = Connection(connection_id, client, counterparty_connection)
        self.connections[connection_id] = conn
        return conn

    def bind_channel(self, channel_id: str, connection_id: str) -> None:
        if connection_id not in self.connections:
            raise ClientError(f"unknown connection {connection_id}")
        self.channel_bindings[channel_id] = connection_id

    def client_for_channel(self, channel_id: str) -> Optional[LightClient]:
        conn_id = self.channel_bindings.get(channel_id)
        if conn_id is None:
            return None
        return self.connections[conn_id].client


# -- store key layout (what proofs point at) ------------------------------


def commitment_key(channel_id: str, seq: int) -> bytes:
    return f"commitments/{channel_id}/{seq}".encode()


def nextseq_key(channel_id: str) -> bytes:
    return f"nextseq/{channel_id}".encode()


def timedout_key(channel_id: str, seq: int) -> bytes:
    return f"timedout/{channel_id}/{seq}".encode()


def packet_commitment(data: bytes, timeout_height: int) -> bytes:
    """What `commitments/{channel}/{seq}` stores: covers the data AND the
    timeout, so a relayer can neither tamper the payload nor stretch the
    packet's deliverability window."""
    return hashlib.sha256(
        timeout_height.to_bytes(8, "big") + data
    ).digest()


def ack_key(channel_id: str, seq: int) -> bytes:
    return f"acks/{channel_id}/{seq}".encode()


def receipt_key(channel_id: str, seq: int) -> bytes:
    return f"receipts/{channel_id}/{seq}".encode()


def channel_key(channel_id: str) -> bytes:
    return f"channels/{channel_id}".encode()


def ack_bytes(ack) -> bytes:
    """Canonical acknowledgement encoding (what the ack commitment
    hashes)."""
    return json.dumps(
        {"success": bool(ack.success), "error": ack.error or ""},
        sort_keys=True,
    ).encode()
