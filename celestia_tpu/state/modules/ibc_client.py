"""IBC light-client layer: trustless packet verification (VERDICT r2 #4).

The round-2 stack moved packets on relayer honesty.  This module adds the
trust machinery the reference gets from ibc-go core + 07-tendermint
clients (/root/reference/app/app.go:339-358):

- ``LightClient`` tracks a counterparty chain's validator set and a map
  height -> ``ConsensusState`` (state root + time).  It advances ONLY on
  a header whose BFT commit certificate verifies: >= 2/3 of the tracked
  power signed precommits over the header's block id
  (node/bft.py vote signatures), and the block id commits to
  ``prev_app_hash`` — so the certificate proves the counterparty's state
  root exactly the way a Tendermint header's AppHash is proven.
- ``Connection`` binds channels to a client (the ICS-3 role, condensed:
  the handshake's proof obligations are the membership checks below).
- Verified packet receive / acknowledgement: the relayer presents a
  merkle membership proof of the packet commitment (or ack) in the
  counterparty's "ibc" store at a proven height; the proof is checked
  against the light client's consensus state with
  state.merkle.verify_query_proof — the relayer is untrusted end to end.

Height convention (Tendermint's): the consensus state recorded at header
height H carries the state root app_hash(H-1); a proof generated against
the store committed at height G therefore verifies with the consensus
state at G+1.

Limitation (documented, round-3 scope): the tracked validator set is
fixed at client creation — valset rotation needs the next-valset hash
committed in the block id, which the payload does not carry yet.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.state.consensus import (
    PRECOMMIT,
    Vote,
    block_id_of,
    vote_sign_bytes,
)
from celestia_tpu.state import merkle
from celestia_tpu.utils.secp256k1 import PublicKey


class ClientError(ValueError):
    pass


# key hashes are 32 bytes, so a valid SMT path never exceeds 256 levels;
# anything longer is a malformed proof, not a deeper tree
_MAX_PROOF_DEPTH = 256


def _checked_proof_verify(proof: dict, root: bytes) -> bool:
    """Run merkle.verify_query_proof on untrusted relayer input, keeping
    the ClientError contract: malformed proofs (bad hex, missing fields,
    oversized sibling paths) FAIL verification instead of escaping as
    ValueError/IndexError/KeyError the callers don't catch."""
    try:
        if len(proof.get("siblings", ())) > _MAX_PROOF_DEPTH:
            raise ClientError(
                f"proof sibling path exceeds {_MAX_PROOF_DEPTH} levels"
            )
        return merkle.verify_query_proof(proof, root)
    except ClientError:
        raise
    except (ValueError, IndexError, KeyError, TypeError, AttributeError) as e:
        raise ClientError(f"malformed proof: {e}") from e


@dataclass(frozen=True)
class ConsensusState:
    root: bytes  # the counterparty app hash proofs verify against
    time_ns: int


class LightClient:
    """07-tendermint analogue over the BFT engine's vote format.

    With a store attached (via ConnectionKeeper), every mutation — new
    consensus states, latest_height, and crucially the misbehaviour
    ``frozen`` flag — is mirrored into merkleized state so a
    disk/snapshot restore brings the client back EXACTLY as it was: a
    client frozen for a proven fork must never come back unfrozen
    (ibc-go persists ClientState/ConsensusState in the ibc store the
    same way)."""

    def __init__(
        self,
        client_id: str,
        chain_id: str,
        validators: Dict[bytes, int],  # operator address -> power
        pubkeys: Dict[bytes, bytes],  # operator address -> 33B compressed
        store=None,
    ):
        if not validators:
            raise ClientError("empty validator set")
        self.client_id = client_id
        self.chain_id = chain_id
        self.validators = dict(validators)
        self.pubkeys = dict(pubkeys)
        self.total_power = sum(validators.values())
        self.consensus_states: Dict[int, ConsensusState] = {}
        self.latest_height = 0
        self.frozen = False
        self.store = store

    # -- persistence ----------------------------------------------------

    def attach_store(self, store) -> None:
        """Mirror the full current state into the given KVStore and keep
        mirroring on every future mutation."""
        self.store = store
        self._persist_identity()
        self._persist_meta()
        for h in self.consensus_states:
            self._persist_consensus(h)

    def _persist_identity(self) -> None:
        """The immutable part — chain id, valset, pubkeys — written once
        at client creation, NOT on every update (the valset can be large
        and never changes for this client's lifetime)."""
        if self.store is None:
            return
        self.store.set(
            client_state_key(self.client_id),
            json.dumps(
                {
                    "chain_id": self.chain_id,
                    "validators": {
                        a.hex(): p for a, p in self.validators.items()
                    },
                    "pubkeys": {
                        a.hex(): pk.hex() for a, pk in self.pubkeys.items()
                    },
                },
                sort_keys=True,
            ).encode(),
        )

    def _persist_meta(self) -> None:
        """The mutable part — frozen flag + latest height — a small O(1)
        record rewritten on every update."""
        if self.store is None:
            return
        self.store.set(
            client_meta_key(self.client_id),
            json.dumps(
                {"frozen": self.frozen, "latest_height": self.latest_height},
                sort_keys=True,
            ).encode(),
        )

    def _persist_consensus(self, height: int) -> None:
        if self.store is None:
            return
        cs = self.consensus_states[height]
        self.store.set(
            consensus_state_store_key(self.client_id, height),
            json.dumps(
                {"root": cs.root.hex(), "time_ns": cs.time_ns},
                sort_keys=True,
            ).encode(),
        )

    @classmethod
    def from_state(cls, client_id: str, d: dict) -> "LightClient":
        """Rebuild a client from its persisted identity record (meta and
        consensus states are rehydrated separately by the keeper)."""
        return cls(
            client_id,
            d["chain_id"],
            {bytes.fromhex(a): int(p) for a, p in d["validators"].items()},
            {
                bytes.fromhex(a): bytes.fromhex(pk)
                for a, pk in d["pubkeys"].items()
            },
        )

    def apply_meta(self, d: dict) -> None:
        self.frozen = bool(d["frozen"])
        self.latest_height = int(d["latest_height"])

    # -- header verification -------------------------------------------

    def update(self, header: dict, precommits: List[dict]) -> int:
        """Verify a (header, commit certificate) pair and record the
        consensus state it proves.  header = BlockPayload.header_fields()
        — the block-id preimage without txs; precommits = Vote wire
        dicts.  Returns the header height.  The caller (relayer) is
        untrusted: everything is checked against the tracked valset."""
        if self.frozen:
            raise ClientError(f"client {self.client_id} is frozen")
        try:
            height = int(header["height"])
            time_ns = int(header["time_ns"])
            square_size = int(header["square_size"])
            # _varint loops forever on negative ints — malformed, not fatal
            if height <= 0 or time_ns < 0 or square_size < 0:
                raise ClientError("header fields out of range")
            prev_app_hash = bytes.fromhex(header["prev_app_hash"])
            block_id = block_id_of(
                height,
                time_ns,
                square_size,
                bytes.fromhex(header["data_root"]),
                bytes.fromhex(header["proposer"]),
                bytes.fromhex(header["last_commit_digest"]),
                prev_app_hash,
            )
            votes = [Vote.from_wire(v) for v in precommits]
        except ClientError:
            raise
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            raise ClientError(f"malformed header/certificate: {e}") from e
        if not votes:
            raise ClientError("empty certificate: below 2/3 power")
        rounds = {v.round for v in votes}
        if len(rounds) != 1:
            raise ClientError("commit certificate mixes rounds")
        seen = set()
        power = 0
        for v in votes:
            if v.vtype != PRECOMMIT or v.height != height:
                raise ClientError("certificate vote is not for this header")
            if v.block_id != block_id:
                raise ClientError("certificate vote is for a different block")
            if v.validator in seen:
                raise ClientError("duplicate validator in certificate")
            seen.add(v.validator)
            vp = self.validators.get(v.validator)
            pk = self.pubkeys.get(v.validator)
            if not vp or pk is None:
                raise ClientError("unknown validator in certificate")
            digest = vote_sign_bytes(
                self.chain_id, v.height, v.round, v.vtype, v.block_id
            )
            if not PublicKey.from_compressed(pk).verify(digest, v.signature):
                raise ClientError("certificate signature does not verify")
            power += vp
        if power * 3 < self.total_power * 2:
            raise ClientError(
                f"certificate power {power} below 2/3 of {self.total_power}"
            )
        # misbehaviour: two CERTIFIED headers at one height with different
        # roots means the counterparty valset double-signed — freeze the
        # client permanently (07-tendermint freezes the same way); a
        # relayer must never be able to pick which fork proofs verify on
        existing = self.consensus_states.get(height)
        if existing is not None and existing.root != prev_app_hash:
            self.frozen = True
            self._persist_meta()  # the freeze must survive a restart
            raise ClientError(
                f"misbehaviour: conflicting certified headers at height "
                f"{height}; client {self.client_id} frozen"
            )
        # Tendermint semantics: the header at H proves app_hash(H-1);
        # record it as the consensus state AT H
        self.consensus_states[height] = ConsensusState(
            root=prev_app_hash, time_ns=time_ns
        )
        self.latest_height = max(self.latest_height, height)
        self._persist_consensus(height)
        self._persist_meta()
        return height

    # -- membership verification ---------------------------------------

    def verify_membership(
        self, proof_height: int, key: bytes, value: bytes, proof: dict
    ) -> None:
        """Raise ClientError unless ``proof`` shows ("ibc", key) == value
        in the counterparty state the consensus state at proof_height
        commits to.  The proof's own claimed key/value/store are checked
        AGAINST THE CALLER'S expectation — a relayer substituting a proof
        of some other key fails here."""
        cs = self.consensus_states.get(proof_height)
        if cs is None:
            raise ClientError(
                f"no consensus state at height {proof_height} "
                f"(client {self.client_id})"
            )
        try:
            if proof.get("store") != "ibc":
                raise ClientError("proof is not for the ibc store")
            if bytes.fromhex(proof["key"]) != key:
                raise ClientError("proof key does not match the packet")
            if proof["value"] is None or bytes.fromhex(proof["value"]) != (
                value
            ):
                raise ClientError("proof value does not match the packet")
        except ClientError:
            raise
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            raise ClientError(f"malformed proof: {e}") from e
        if not _checked_proof_verify(proof, cs.root):
            raise ClientError(
                "membership proof does not verify against the consensus state"
            )

    def verify_non_membership(
        self, proof_height: int, key: bytes, proof: dict
    ) -> None:
        """Absence proof (timeouts: the counterparty never wrote a
        receipt for the packet)."""
        cs = self.consensus_states.get(proof_height)
        if cs is None:
            raise ClientError(f"no consensus state at height {proof_height}")
        try:
            if proof.get("store") != "ibc":
                raise ClientError("proof is not for the ibc store")
            if bytes.fromhex(proof["key"]) != key:
                raise ClientError("proof key does not match")
            if proof["value"] is not None:
                raise ClientError("expected an absence proof")
        except ClientError:
            raise
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            raise ClientError(f"malformed proof: {e}") from e
        if not _checked_proof_verify(proof, cs.root):
            raise ClientError(
                "absence proof does not verify against the consensus state"
            )


@dataclass
class Connection:
    """ICS-3 condensed: a named binding of channels to a light client."""

    connection_id: str
    client: LightClient
    counterparty_connection: str = ""


class ConnectionKeeper:
    """Client/connection/binding registry.  With a store attached (the
    app's "ibc" substore, shared with ChannelKeeper under disjoint key
    prefixes) everything here is mirrored to merkleized state and
    rehydrated after a disk/snapshot restore — clients, their consensus
    states and frozen flags, connections, and channel bindings all
    survive a restart alongside the receipts/commitments the channel
    keeper already persists."""

    def __init__(self, store=None):
        self.store = store
        self.clients: Dict[str, LightClient] = {}
        self.connections: Dict[str, Connection] = {}
        # channel_id -> connection_id: which client secures which channel
        self.channel_bindings: Dict[str, str] = {}

    def rehydrate(self) -> None:
        """Rebuild clients, connections and bindings from the store."""
        if self.store is None:
            return
        consensus_rows = []
        meta_rows: Dict[str, dict] = {}
        connection_rows: Dict[str, dict] = {}
        for k, v in self.store.iterate():
            parts = k.decode().split("/")
            if parts[0] == "clients" and len(parts) == 3 and (
                parts[2] == "state"
            ):
                self.clients[parts[1]] = LightClient.from_state(
                    parts[1], json.loads(v)
                )
            elif parts[0] == "clients" and len(parts) == 3 and (
                parts[2] == "meta"
            ):
                meta_rows[parts[1]] = json.loads(v)
            elif parts[0] == "clients" and len(parts) == 4 and (
                parts[2] == "consensus"
            ):
                consensus_rows.append((parts[1], int(parts[3]), json.loads(v)))
            elif parts[0] == "connections" and len(parts) == 2:
                connection_rows[parts[1]] = json.loads(v)
            elif parts[0] == "channelclients" and len(parts) == 2:
                self.channel_bindings[parts[1]] = v.decode()
        for cid, meta in meta_rows.items():
            client = self.clients.get(cid)
            if client is not None:
                client.apply_meta(meta)
        for cid, height, d in consensus_rows:
            client = self.clients.get(cid)
            if client is not None:
                client.consensus_states[height] = ConsensusState(
                    root=bytes.fromhex(d["root"]), time_ns=int(d["time_ns"])
                )
        for client in self.clients.values():
            client.store = self.store  # future mutations keep mirroring
        for conn_id, d in connection_rows.items():
            client = self.clients.get(d["client_id"])
            if client is not None:
                self.connections[conn_id] = Connection(
                    conn_id, client, d.get("counterparty_connection", "")
                )

    def create_client(self, client: LightClient) -> None:
        if client.client_id in self.clients:
            raise ClientError(f"client {client.client_id} exists")
        self.clients[client.client_id] = client
        if self.store is not None:
            client.attach_store(self.store)

    def open_connection(
        self, connection_id: str, client_id: str,
        counterparty_connection: str = "",
    ) -> Connection:
        client = self.clients.get(client_id)
        if client is None:
            raise ClientError(f"unknown client {client_id}")
        conn = Connection(connection_id, client, counterparty_connection)
        self.connections[connection_id] = conn
        if self.store is not None:
            self.store.set(
                connection_store_key(connection_id),
                json.dumps(
                    {
                        "client_id": client_id,
                        "counterparty_connection": counterparty_connection,
                    },
                    sort_keys=True,
                ).encode(),
            )
        return conn

    def bind_channel(self, channel_id: str, connection_id: str) -> None:
        if connection_id not in self.connections:
            raise ClientError(f"unknown connection {connection_id}")
        self.channel_bindings[channel_id] = connection_id
        if self.store is not None:
            self.store.set(
                channel_binding_key(channel_id), connection_id.encode()
            )

    def client_for_channel(self, channel_id: str) -> Optional[LightClient]:
        conn_id = self.channel_bindings.get(channel_id)
        if conn_id is None:
            return None
        conn = self.connections.get(conn_id)
        return conn.client if conn is not None else None


# -- store key layout (what proofs point at) ------------------------------


def client_state_key(client_id: str) -> bytes:
    return f"clients/{client_id}/state".encode()


def client_meta_key(client_id: str) -> bytes:
    return f"clients/{client_id}/meta".encode()


def consensus_state_store_key(client_id: str, height: int) -> bytes:
    return f"clients/{client_id}/consensus/{height}".encode()


def connection_store_key(connection_id: str) -> bytes:
    return f"connections/{connection_id}".encode()


def channel_binding_key(channel_id: str) -> bytes:
    return f"channelclients/{channel_id}".encode()


def commitment_key(channel_id: str, seq: int) -> bytes:
    return f"commitments/{channel_id}/{seq}".encode()


def nextseq_key(channel_id: str) -> bytes:
    return f"nextseq/{channel_id}".encode()


def timedout_key(channel_id: str, seq: int) -> bytes:
    return f"timedout/{channel_id}/{seq}".encode()


def packet_commitment(data: bytes, timeout_height: int) -> bytes:
    """What `commitments/{channel}/{seq}` stores: covers the data AND the
    timeout, so a relayer can neither tamper the payload nor stretch the
    packet's deliverability window."""
    return hashlib.sha256(
        timeout_height.to_bytes(8, "big") + data
    ).digest()


def ack_key(channel_id: str, seq: int) -> bytes:
    return f"acks/{channel_id}/{seq}".encode()


def receipt_key(channel_id: str, seq: int) -> bytes:
    return f"receipts/{channel_id}/{seq}".encode()


def channel_key(channel_id: str) -> bytes:
    return f"channels/{channel_id}".encode()


def ack_bytes(ack) -> bytes:
    """Canonical acknowledgement encoding (what the ack commitment
    hashes)."""
    return json.dumps(
        {"success": bool(ack.success), "error": ack.error or ""},
        sort_keys=True,
    ).encode()
