"""Post-handler chain: decorators that run AFTER a tx's messages execute.

Parity with /root/reference/app/posthandler/posthandler.go:1-12 — the
reference's chain is deliberately EMPTY (a placeholder for future
post-execution logic such as fee refunds or tip routing), but the
chain MECHANISM is wired: BaseApp calls the post handler on the message
branch after successful execution, so post-decorator writes commit (or
roll back) atomically with the tx.  This module mirrors that: the
default chain is empty, `new_post_handler()` composes any registered
decorators in order, and App.deliver_tx runs the chain on the message
branch after the last message succeeds (state/app.py).

A post decorator is `fn(ctx: PostContext) -> None`; raising rolls the
whole tx back (same atomicity as a message failure).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Tuple


@dataclass
class PostContext:
    """What a post decorator sees: the executed tx, its events, and the
    app (for keeper access on the current — message-branch — store)."""

    tx: object
    app: object
    events: List[dict] = field(default_factory=list)
    gas_meter: object = None


PostDecorator = Callable[[PostContext], None]

# posthandler.go:10 — the default chain is empty on purpose
DEFAULT_POST_DECORATORS: Tuple[PostDecorator, ...] = ()


def new_post_handler(
    decorators: Tuple[PostDecorator, ...] = DEFAULT_POST_DECORATORS,
) -> Callable[[PostContext], None]:
    """ChainAnteDecorators parity for the post chain: compose decorators
    in order into one callable."""

    def run(ctx: PostContext) -> None:
        for dec in decorators:
            dec(ctx)

    return run
