"""Versioned key-value multistore with Merkle app hash and copy-on-write
branches.

Role parity with the reference's IAVL/LevelDB commit-multistore (SURVEY.md
§2.1 "framework": baseapp stores): namespaced substores per module, branch/
cache-wrap semantics for speculative execution (CheckTx / proposal
processing / per-tx delivery), commit-per-height versioning with app-hash,
load-at-height rollback, and full export/import for genesis and state-sync
-style snapshots.

Branches are overlay stores (write layer + read-through to the parent), so
branching is O(1) and a branch costs O(its own writes) — the cache-wrap
semantics of the SDK's CacheMultiStore.  The app hash is a deterministic
SHA-256 over sorted (store, key, value) triples so every validator computes
the identical hash for identical state.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterator, List, Optional, Set, Tuple


class _DictLayer:
    """Base storage layer backed by a plain dict."""

    def __init__(self, data: Optional[Dict[bytes, bytes]] = None):
        self.data: Dict[bytes, bytes] = data if data is not None else {}

    def get(self, key: bytes) -> Optional[bytes]:
        return self.data.get(key)

    def has(self, key: bytes) -> bool:
        return key in self.data

    def set(self, key: bytes, value: bytes) -> None:
        self.data[key] = value

    def delete(self, key: bytes) -> None:
        self.data.pop(key, None)

    def keys(self) -> Set[bytes]:
        return set(self.data)


class _OverlayLayer:
    """Copy-on-write layer: local writes/deletes over a parent layer."""

    def __init__(self, parent):
        self.parent = parent
        self.writes: Dict[bytes, bytes] = {}
        self.deletes: Set[bytes] = set()

    def get(self, key: bytes) -> Optional[bytes]:
        if key in self.writes:
            return self.writes[key]
        if key in self.deletes:
            return None
        return self.parent.get(key)

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        self.writes[key] = value
        self.deletes.discard(key)

    def delete(self, key: bytes) -> None:
        self.writes.pop(key, None)
        self.deletes.add(key)

    def keys(self) -> Set[bytes]:
        return (self.parent.keys() - self.deletes) | set(self.writes)

    def apply_to_parent(self) -> None:
        for k, v in self.writes.items():
            self.parent.set(k, v)
        for k in self.deletes:
            self.parent.delete(k)
        self.writes.clear()
        self.deletes.clear()


class KVStore:
    """A single namespaced store view."""

    def __init__(self, layer, name: str = "", tracer_ref=None):
        self._layer = layer
        self._name = name
        # shared mutable holder [callable | None] owned by the MultiStore —
        # installing a tracer after KVStores were handed out still traces
        self._tracer_ref = tracer_ref if tracer_ref is not None else [None]

    def get(self, key: bytes) -> Optional[bytes]:
        return self._layer.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(key, bytes) or not isinstance(value, bytes):
            raise TypeError("keys and values must be bytes")
        tracer = self._tracer_ref[0]
        if tracer is not None:
            tracer("write", self._name, key, value)
        self._layer.set(key, value)

    def delete(self, key: bytes) -> None:
        tracer = self._tracer_ref[0]
        if tracer is not None:
            tracer("delete", self._name, key, None)
        self._layer.delete(key)

    def has(self, key: bytes) -> bool:
        return self._layer.has(key)

    def iterate(self, prefix: bytes = b"") -> Iterator[Tuple[bytes, bytes]]:
        """Deterministic (sorted) iteration over keys with the prefix."""
        for k in sorted(self._layer.keys()):
            if k.startswith(prefix):
                v = self._layer.get(k)
                if v is not None:
                    yield k, v


class MultiStore:
    """Named substores + commit versioning + O(1) overlay branching."""

    def __init__(self, store_names: List[str]):
        self._names = list(store_names)
        self._layers: Dict[str, object] = {n: _DictLayer() for n in store_names}
        self._versions: List[Tuple[int, Dict[str, Dict[bytes, bytes]], bytes]] = []
        self._last_height = 0
        self._parent: Optional["MultiStore"] = None
        self._tracer_ref: List[Optional[object]] = [None]

    def set_tracer(self, tracer) -> None:
        """Install a write tracer: tracer(op, store_name, key, value) fires
        on every set/delete through this store's views (the reference's
        SetCommitMultiStoreTracer role, app/app.go:243).  Pass None to
        remove.  Branches created AFTER installation inherit it."""
        self._tracer_ref[0] = tracer

    def store(self, name: str) -> KVStore:
        if name not in self._layers:
            raise KeyError(f"unknown store {name!r}")
        return KVStore(self._layers[name], name, self._tracer_ref)

    @property
    def store_names(self) -> List[str]:
        return list(self._names)

    def ensure_store(self, name: str) -> None:
        """Mount a new substore (upgrade-time store additions)."""
        if name not in self._layers:
            self._names.append(name)
            self._layers[name] = _DictLayer()

    # --- branching (CacheMultiStore semantics) ----------------------------

    def branch(self) -> "MultiStore":
        ms = MultiStore.__new__(MultiStore)
        ms._names = list(self._names)
        ms._layers = {n: _OverlayLayer(layer) for n, layer in self._layers.items()}
        ms._versions = []
        ms._last_height = self._last_height
        ms._parent = self
        ms._tracer_ref = self._tracer_ref  # branches trace through the root
        return ms

    def write_back(self, branched: "MultiStore") -> None:
        """Apply a branch's writes to this store (the branch must have been
        created from this store)."""
        if branched._parent is not self:
            raise ValueError("write_back: branch does not belong to this store")
        for layer in branched._layers.values():
            layer.apply_to_parent()

    # --- commit / versions ------------------------------------------------

    def _flatten(self, name: str) -> Dict[bytes, bytes]:
        layer = self._layers[name]
        return {k: layer.get(k) for k in layer.keys()}

    def app_hash(self) -> bytes:
        h = hashlib.sha256()
        for name in sorted(self._layers):
            data = self._flatten(name)
            for k in sorted(data):
                h.update(hashlib.sha256(name.encode() + b"\x00" + k).digest())
                h.update(hashlib.sha256(data[k]).digest())
        return h.digest()

    def commit(self, height: int) -> bytes:
        if self._parent is not None:
            raise ValueError("cannot commit a branched store")
        if height <= self._last_height:
            raise ValueError(
                f"commit height {height} must be > last committed {self._last_height}"
            )
        snapshot = {n: dict(self._flatten(n)) for n in self._layers}
        ah = self.app_hash()
        self._versions.append((height, snapshot, ah))
        self._last_height = height
        return ah

    @property
    def last_height(self) -> int:
        return self._last_height

    def commit_at(self, height: int, app_hash: bytes) -> None:
        """Record the current state as the committed version at ``height``
        (snapshot restore: the store resumes as if it had committed there)."""
        if self._parent is not None:
            raise ValueError("cannot commit a branched store")
        snapshot = {n: dict(self._flatten(n)) for n in self._layers}
        self._versions.append((height, snapshot, app_hash))
        self._last_height = height

    def prune(self, keep_recent: int) -> None:
        if keep_recent > 0 and len(self._versions) > keep_recent:
            self._versions = self._versions[-keep_recent:]

    def load_height(self, height: int) -> None:
        """Roll the working state back to a committed version
        (app.LoadHeight parity, app/app.go:729)."""
        for h, snap, _ in self._versions:
            if h == height:
                self._layers = {n: _DictLayer(dict(d)) for n, d in snap.items()}
                self._names = sorted(snap)
                self._last_height = h
                self._versions = [v for v in self._versions if v[0] <= height]
                return
        raise KeyError(f"no committed version at height {height}")

    def committed_hash(self, height: int) -> bytes:
        for h, _, ah in self._versions:
            if h == height:
                return ah
        raise KeyError(f"no committed version at height {height}")

    # --- export / import (genesis + snapshots) ----------------------------

    def export(self) -> Dict[str, Dict[str, str]]:
        """JSON-able dump of all stores (hex keys/values)."""
        return {
            n: {k.hex(): v.hex() for k, v in sorted(self._flatten(n).items())}
            for n in self._layers
        }

    @classmethod
    def import_state(cls, dump: Dict[str, Dict[str, str]]) -> "MultiStore":
        ms = cls(sorted(dump))
        for n, d in dump.items():
            ms._layers[n] = _DictLayer(
                {bytes.fromhex(k): bytes.fromhex(v) for k, v in d.items()}
            )
        return ms
