"""Versioned key-value multistore with an incrementally-maintained Merkle
app hash, copy-on-write branches, and pluggable disk persistence.

Role parity with the reference's IAVL/LevelDB commit-multistore (SURVEY.md
§2.1 "framework": baseapp stores, mounted at app/app.go:242): namespaced
substores per module, branch/cache-wrap semantics for speculative execution
(CheckTx / proposal processing / per-tx delivery), commit-per-height
versioning with app-hash, load-at-height rollback, height-pinned reads with
membership proofs, and full export/import for genesis and state-sync-style
snapshots.

Unlike the round-2 design (flatten + rehash all state per commit, full
deep-copy per height), commits now cost O(writes * log N):

- each substore keeps a compact sparse Merkle tree (state.merkle) over
  (sha256(key) -> sha256(value)); only keys written since the last commit
  are re-folded;
- the app hash is the hash of the sorted (store name, store root) pairs;
- history is kept as per-height REVERSE diffs (the values each block
  overwrote), bounded by ``history_keep``, so memory stays flat over long
  chains while recent heights remain queryable, provable and rollbackable;
- a persister callback receives every commit's forward diff for the
  append-only disk log (state.disk), which is what crash recovery replays.

Branches are overlay stores (write layer + read-through to the parent), so
branching is O(1) and a branch costs O(its own writes) — the cache-wrap
semantics of the SDK's CacheMultiStore.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from celestia_tpu.state import merkle
from celestia_tpu.state.merkle import EMPTY_ROOT


class _DictLayer:
    """Base storage layer backed by a plain dict, tracking per-commit
    write provenance: ``prev`` holds each key's value before its first
    write since the last commit (None = was absent) and ``unsynced``
    holds keys whose merkle leaves are stale."""

    def __init__(self, data: Optional[Dict[bytes, bytes]] = None):
        self.data: Dict[bytes, bytes] = data if data is not None else {}
        self.prev: Dict[bytes, Optional[bytes]] = {}
        self.unsynced: Set[bytes] = set()

    def get(self, key: bytes) -> Optional[bytes]:
        return self.data.get(key)

    def has(self, key: bytes) -> bool:
        return key in self.data

    def set(self, key: bytes, value: bytes) -> None:
        if key not in self.prev:
            self.prev[key] = self.data.get(key)
        self.unsynced.add(key)
        self.data[key] = value

    def delete(self, key: bytes) -> None:
        if key in self.data:
            if key not in self.prev:
                self.prev[key] = self.data[key]
            self.unsynced.add(key)
            del self.data[key]

    def keys(self) -> Set[bytes]:
        return set(self.data)


class _OverlayLayer:
    """Copy-on-write layer: local writes/deletes over a parent layer."""

    def __init__(self, parent):
        self.parent = parent
        self.writes: Dict[bytes, bytes] = {}
        self.deletes: Set[bytes] = set()

    def get(self, key: bytes) -> Optional[bytes]:
        if key in self.writes:
            return self.writes[key]
        if key in self.deletes:
            return None
        return self.parent.get(key)

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def set(self, key: bytes, value: bytes) -> None:
        self.writes[key] = value
        self.deletes.discard(key)

    def delete(self, key: bytes) -> None:
        self.writes.pop(key, None)
        self.deletes.add(key)

    def keys(self) -> Set[bytes]:
        return (self.parent.keys() - self.deletes) | set(self.writes)

    def apply_to_parent(self) -> None:
        for k, v in self.writes.items():
            self.parent.set(k, v)
        for k in self.deletes:
            self.parent.delete(k)
        self.writes.clear()
        self.deletes.clear()


class KVStore:
    """A single namespaced store view."""

    def __init__(self, layer, name: str = "", tracer_ref=None):
        self._layer = layer
        self._name = name
        # shared mutable holder [callable | None] owned by the MultiStore —
        # installing a tracer after KVStores were handed out still traces
        self._tracer_ref = tracer_ref if tracer_ref is not None else [None]

    def get(self, key: bytes) -> Optional[bytes]:
        return self._layer.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(key, bytes) or not isinstance(value, bytes):
            raise TypeError("keys and values must be bytes")
        tracer = self._tracer_ref[0]
        if tracer is not None:
            tracer("write", self._name, key, value)
        self._layer.set(key, value)

    def delete(self, key: bytes) -> None:
        tracer = self._tracer_ref[0]
        if tracer is not None:
            tracer("delete", self._name, key, None)
        self._layer.delete(key)

    def has(self, key: bytes) -> bool:
        return self._layer.has(key)

    def iterate(self, prefix: bytes = b"") -> Iterator[Tuple[bytes, bytes]]:
        """Deterministic (sorted) iteration over keys with the prefix."""
        for k in sorted(self._layer.keys()):
            if k.startswith(prefix):
                v = self._layer.get(k)
                if v is not None:
                    yield k, v


# forward diff: key -> new value (None = deleted)
Diff = Dict[bytes, Optional[bytes]]


class MultiStore:
    """Named substores + merkleized commit versioning + O(1) branching."""

    def __init__(self, store_names: List[str], history_keep: int = 256):
        self._names = list(store_names)
        self._layers: Dict[str, object] = {n: _DictLayer() for n in store_names}
        self._parent: Optional["MultiStore"] = None
        self._tracer_ref: List[Optional[object]] = [None]
        # merkle state: content-addressed nodes shared by every store tree
        self._nodes: Dict[bytes, bytes] = {}
        self._roots: Optional[Dict[str, bytes]] = None  # None = never built
        # committed history: (height, app_hash, {store: root}) + the values
        # each block overwrote, bounded to history_keep recent heights
        self._meta: List[Tuple[int, bytes, Dict[str, bytes]]] = []
        self._reverse_diffs: Dict[int, Dict[str, Diff]] = {}
        self.history_keep = history_keep
        self._gc_interval = 64
        self._commits_since_gc = 0
        self._last_height = 0
        self._persister: Optional[Callable] = None

    # --- wiring -----------------------------------------------------------

    def set_tracer(self, tracer) -> None:
        """Install a write tracer: tracer(op, store_name, key, value) fires
        on every set/delete through this store's views (the reference's
        SetCommitMultiStoreTracer role, app/app.go:243).  Pass None to
        remove.  Branches created AFTER installation inherit it."""
        self._tracer_ref[0] = tracer

    def set_persister(self, persister: Optional[Callable]) -> None:
        """persister(height, app_hash, roots, {store: forward_diff}) is
        called on every commit — the disk log's feed (state.disk)."""
        self._persister = persister

    def store(self, name: str) -> KVStore:
        if name not in self._layers:
            raise KeyError(f"unknown store {name!r}")
        return KVStore(self._layers[name], name, self._tracer_ref)

    @property
    def store_names(self) -> List[str]:
        return list(self._names)

    def ensure_store(self, name: str) -> None:
        """Mount a new substore (upgrade-time store additions)."""
        if name not in self._layers:
            self._names.append(name)
            self._layers[name] = _DictLayer()
            if self._roots is not None:
                self._roots[name] = EMPTY_ROOT

    # --- branching (CacheMultiStore semantics) ----------------------------

    def branch(self) -> "MultiStore":
        ms = MultiStore.__new__(MultiStore)
        ms._names = list(self._names)
        ms._layers = {n: _OverlayLayer(layer) for n, layer in self._layers.items()}
        ms._parent = self
        ms._tracer_ref = self._tracer_ref  # branches trace through the root
        ms._nodes = {}
        ms._roots = None
        ms._meta = []
        ms._reverse_diffs = {}
        ms.history_keep = self.history_keep
        ms._gc_interval = self._gc_interval
        ms._commits_since_gc = 0
        ms._last_height = self._last_height
        ms._persister = None
        return ms

    def write_back(self, branched: "MultiStore") -> None:
        """Apply a branch's writes to this store (the branch must have been
        created from this store)."""
        if branched._parent is not self:
            raise ValueError("write_back: branch does not belong to this store")
        for layer in branched._layers.values():
            layer.apply_to_parent()

    def overlay_delta(self) -> Dict[str, Tuple[Dict[bytes, bytes], Set[bytes]]]:
        """Snapshot of this BRANCH's pending writes: {store: (writes,
        deletes)} for every substore the branch touched.  The captured
        per-tx delta is what the parallel FilterTxs fold replays
        sequentially in priority order (state/app.py) — capture happens
        before write_back, which clears the overlay."""
        if self._parent is None:
            raise ValueError("overlay_delta: not a branched store")
        out: Dict[str, Tuple[Dict[bytes, bytes], Set[bytes]]] = {}
        for name, layer in self._layers.items():
            if layer.writes or layer.deletes:
                out[name] = (dict(layer.writes), set(layer.deletes))
        return out

    def apply_overlay_delta(
        self, delta: Dict[str, Tuple[Dict[bytes, bytes], Set[bytes]]]
    ) -> None:
        """Replay a captured overlay delta through this store's views
        (writes first, then deletes — apply_to_parent order)."""
        for name, (writes, deletes) in delta.items():
            st = self.store(name)
            for k, v in writes.items():
                st.set(k, v)
            for k in deletes:
                st.delete(k)

    # --- merkle sync ------------------------------------------------------

    def _sync_smt(self) -> Dict[str, bytes]:
        """Fold pending writes into the store trees; O(writes * log N)."""
        if self._parent is not None:
            raise ValueError("branched stores carry no merkle state")
        if self._roots is None:
            # first build (fresh store or state-sync import): everything
            self._roots = {}
            for name in self._names:
                layer = self._layers[name]
                self._roots[name] = merkle.smt_build(
                    self._nodes,
                    sorted(
                        (merkle.key_hash(k), merkle.value_hash(v))
                        for k, v in layer.data.items()
                    ),
                )
                layer.unsynced.clear()
            return self._roots
        for name in self._names:
            layer = self._layers[name]
            if not layer.unsynced:
                continue
            root = self._roots.get(name, EMPTY_ROOT)
            for k in sorted(layer.unsynced):
                kh = merkle.key_hash(k)
                v = layer.data.get(k)
                if v is None:
                    root = merkle.smt_delete(self._nodes, root, kh)
                else:
                    root = merkle.smt_update(
                        self._nodes, root, kh, merkle.value_hash(v)
                    )
            self._roots[name] = root
            layer.unsynced.clear()
        return self._roots

    def app_hash(self) -> bytes:
        """Root-of-store-roots over current state (pending writes
        included).  Idempotent; does not create a version."""
        return merkle.store_roots_hash(self._sync_smt())

    # --- commit / versions ------------------------------------------------

    def commit(self, height: int) -> bytes:
        if self._parent is not None:
            raise ValueError("cannot commit a branched store")
        if height <= self._last_height:
            raise ValueError(
                f"commit height {height} must be > last committed {self._last_height}"
            )
        roots = dict(self._sync_smt())
        ah = merkle.store_roots_hash(roots)
        forward: Dict[str, Diff] = {}
        reverse: Dict[str, Diff] = {}
        for name in self._names:
            layer = self._layers[name]
            if not layer.prev:
                continue
            reverse[name] = dict(layer.prev)
            forward[name] = {k: layer.data.get(k) for k in layer.prev}
            layer.prev.clear()
        self._meta.append((height, ah, roots))
        self._reverse_diffs[height] = reverse
        self._last_height = height
        if self._persister is not None:
            self._persister(height, ah, roots, forward)
        self._trim_history()
        return ah

    def _trim_history(self) -> None:
        if self.history_keep <= 0:
            return
        if len(self._meta) > self.history_keep:
            for h, _, _ in self._meta[: -self.history_keep]:
                self._reverse_diffs.pop(h, None)
            self._meta = self._meta[-self.history_keep:]
        self._commits_since_gc += 1
        if self._commits_since_gc >= self._gc_interval:
            self._gc_nodes()

    def _gc_nodes(self) -> None:
        """Drop merkle nodes unreachable from any retained root."""
        self._commits_since_gc = 0
        roots: Set[bytes] = set()
        if self._roots:
            roots.update(self._roots.values())
        for _, _, rts in self._meta:
            roots.update(rts.values())
        live = merkle.smt_reachable(self._nodes, roots)
        self._nodes = {h: e for h, e in self._nodes.items() if h in live}

    @property
    def last_height(self) -> int:
        return self._last_height

    def commit_at(self, height: int, app_hash: bytes) -> None:
        """Record the current state as the committed version at ``height``
        (snapshot restore: the store resumes as if it had committed there)."""
        if self._parent is not None:
            raise ValueError("cannot commit a branched store")
        roots = dict(self._sync_smt())
        for layer in self._layers.values():
            layer.prev.clear()
        self._meta.append((height, app_hash, roots))
        self._reverse_diffs[height] = {}
        self._last_height = height

    def prune(self, keep_recent: int) -> None:
        if keep_recent > 0 and len(self._meta) > keep_recent:
            for h, _, _ in self._meta[:-keep_recent]:
                self._reverse_diffs.pop(h, None)
            self._meta = self._meta[-keep_recent:]
        self._gc_nodes()

    def _meta_at(self, height: int) -> Tuple[int, bytes, Dict[str, bytes]]:
        for m in self._meta:
            if m[0] == height:
                return m
        raise KeyError(f"no committed version at height {height}")

    def load_height(self, height: int) -> None:
        """Roll the working state back to a committed version
        (app.LoadHeight parity, app/app.go:729) by unwinding the reverse
        diffs of every later block.  Only heights inside the retained
        history window can be loaded."""
        _, ah, roots = self._meta_at(height)
        # discard uncommitted writes first (restore pre-values)
        for layer in self._layers.values():
            for k, v in layer.prev.items():
                if v is None:
                    layer.data.pop(k, None)
                else:
                    layer.data[k] = v
            layer.prev.clear()
            layer.unsynced.clear()
        for h in sorted(
            (h for h in self._reverse_diffs if h > height), reverse=True
        ):
            for name, diff in self._reverse_diffs[h].items():
                layer = self._layers[name]
                for k, v in diff.items():
                    if v is None:
                        layer.data.pop(k, None)
                    else:
                        layer.data[k] = v
        for h in [h for h in self._reverse_diffs if h > height]:
            del self._reverse_diffs[h]
        self._meta = [m for m in self._meta if m[0] <= height]
        self._roots = dict(roots)
        self._last_height = height

    def committed_hash(self, height: int) -> bytes:
        return self._meta_at(height)[1]

    def committed_roots(self, height: int) -> Dict[str, bytes]:
        return dict(self._meta_at(height)[2])

    # --- height-pinned reads + proofs ------------------------------------

    def get_at(self, name: str, key: bytes, height: int) -> Optional[bytes]:
        """The value of ``key`` as of committed ``height`` (i.e. after
        block ``height`` executed), reconstructed from reverse diffs."""
        self._meta_at(height)  # raises if outside the retained window
        layer = self._layers[name]
        # last committed value = current, unless dirtied since last commit
        if key in layer.prev:
            value = layer.prev[key]
        else:
            value = layer.data.get(key)
        for h in sorted(
            (h for h in self._reverse_diffs if h > height), reverse=True
        ):
            diff = self._reverse_diffs[h].get(name)
            if diff is not None and key in diff:
                value = diff[key]
        return value

    def prove(self, name: str, key: bytes, height: Optional[int] = None) -> dict:
        """Membership / non-membership proof of ``key`` in store ``name``
        at committed ``height`` (default: latest).  The returned dict
        carries everything a client needs to verify against the block's
        app hash: the value, the sibling path, the terminal leaf, and ALL
        store roots (to recompute the root-of-store-roots).
        Verify with state.merkle.verify_query_proof."""
        if height is None:
            height = self._last_height
        h, ah, roots = self._meta_at(height)
        if name not in roots:
            raise KeyError(f"unknown store {name!r} at height {height}")
        value = self.get_at(name, key, height)
        siblings, leaf = merkle.smt_prove(
            self._nodes, roots[name], merkle.key_hash(key)
        )
        return {
            "height": h,
            "app_hash": ah.hex(),
            "store": name,
            "key": key.hex(),
            "value": value.hex() if value is not None else None,
            "siblings": [s.hex() for s in siblings],
            "leaf": [leaf[0].hex(), leaf[1].hex()] if leaf else None,
            "store_roots": {n: r.hex() for n, r in sorted(roots.items())},
        }

    # --- export / import (genesis + snapshots) ----------------------------

    def _flatten(self, name: str) -> Dict[bytes, bytes]:
        layer = self._layers[name]
        return {k: layer.get(k) for k in layer.keys()}

    def raw_state(self) -> Dict[str, Dict[bytes, bytes]]:
        """Bytes-level snapshot of all stores (disk checkpoint feed)."""
        return {n: dict(self._layers[n].data) for n in self._names}

    def export(self) -> Dict[str, Dict[str, str]]:
        """JSON-able dump of all stores (hex keys/values)."""
        return {
            n: {k.hex(): v.hex() for k, v in sorted(self._flatten(n).items())}
            for n in self._layers
        }

    @classmethod
    def import_state(cls, dump: Dict[str, Dict[str, str]]) -> "MultiStore":
        ms = cls(sorted(dump))
        for n, d in dump.items():
            ms._layers[n] = _DictLayer(
                {bytes.fromhex(k): bytes.fromhex(v) for k, v in d.items()}
            )
        return ms

    @classmethod
    def from_raw(cls, state: Dict[str, Dict[bytes, bytes]]) -> "MultiStore":
        """Adopt an already-decoded state map (disk-log recovery)."""
        ms = cls(sorted(state))
        for n, d in state.items():
            ms._layers[n] = _DictLayer(dict(d))
        return ms

    def apply_diff(self, diffs: Dict[str, Diff]) -> None:
        """Apply a forward diff (disk-log replay).  Writes go through the
        layers so merkle sync and the next commit's reverse diff see them."""
        for name, diff in diffs.items():
            self.ensure_store(name)
            layer = self._layers[name]
            for k, v in diff.items():
                if v is None:
                    layer.delete(k)
                else:
                    layer.set(k, v)
