"""Versioned key-value multistore with Merkle app hash.

Role parity with the reference's IAVL/LevelDB commit-multistore (SURVEY.md
§2.1 "framework": baseapp stores): namespaced substores per module, branch/
cache-wrap semantics for speculative execution (CheckTx / proposal
processing), commit-per-height versioning with app-hash, load-at-height
rollback, and full export/import for genesis and state-sync-style snapshots.

Implementation is an in-memory copy-on-write dict (this framework's node is
a library/devnet runtime, not a disk daemon yet); the app hash is a
deterministic SHA-256 over sorted (store, key, value) triples so every
validator computes the identical hash for identical state.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterator, List, Optional, Tuple


class KVStore:
    """A single namespaced store view backed by a dict."""

    def __init__(self, data: Dict[bytes, bytes]):
        self._data = data

    def get(self, key: bytes) -> Optional[bytes]:
        return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(key, bytes) or not isinstance(value, bytes):
            raise TypeError("keys and values must be bytes")
        self._data[key] = value

    def delete(self, key: bytes) -> None:
        self._data.pop(key, None)

    def has(self, key: bytes) -> bool:
        return key in self._data

    def iterate(self, prefix: bytes = b"") -> Iterator[Tuple[bytes, bytes]]:
        """Deterministic (sorted) iteration over keys with the prefix."""
        for k in sorted(self._data):
            if k.startswith(prefix):
                yield k, self._data[k]


class MultiStore:
    """Named substores + commit versioning.

    ``branch()`` returns a deep-copied speculative store (the SDK's
    CacheMultiStore used by CheckTx and proposal handling); ``commit()``
    seals a version and returns the app hash.
    """

    def __init__(self, store_names: List[str]):
        self._names = list(store_names)
        self._stores: Dict[str, Dict[bytes, bytes]] = {n: {} for n in store_names}
        self._versions: List[Tuple[int, Dict[str, Dict[bytes, bytes]], bytes]] = []
        self._last_height = 0

    def store(self, name: str) -> KVStore:
        if name not in self._stores:
            raise KeyError(f"unknown store {name!r}")
        return KVStore(self._stores[name])

    @property
    def store_names(self) -> List[str]:
        return list(self._names)

    def ensure_store(self, name: str) -> None:
        """Mount a new substore (upgrade-time store additions)."""
        if name not in self._stores:
            self._names.append(name)
            self._stores[name] = {}

    # --- branching --------------------------------------------------------

    def branch(self) -> "MultiStore":
        ms = MultiStore(self._names)
        ms._stores = {n: dict(d) for n, d in self._stores.items()}
        ms._last_height = self._last_height
        return ms

    def write_back(self, branched: "MultiStore") -> None:
        """Apply a branched store's state over this one (ante success path)."""
        self._stores = {n: dict(d) for n, d in branched._stores.items()}

    # --- commit / versions ------------------------------------------------

    def app_hash(self) -> bytes:
        h = hashlib.sha256()
        for name in sorted(self._stores):
            data = self._stores[name]
            for k in sorted(data):
                h.update(hashlib.sha256(name.encode() + b"\x00" + k).digest())
                h.update(hashlib.sha256(data[k]).digest())
        return h.digest()

    def commit(self, height: int) -> bytes:
        if height <= self._last_height:
            raise ValueError(
                f"commit height {height} must be > last committed {self._last_height}"
            )
        snapshot = {n: dict(d) for n, d in self._stores.items()}
        ah = self.app_hash()
        self._versions.append((height, snapshot, ah))
        self._last_height = height
        return ah

    @property
    def last_height(self) -> int:
        return self._last_height

    def prune(self, keep_recent: int) -> None:
        if keep_recent > 0 and len(self._versions) > keep_recent:
            self._versions = self._versions[-keep_recent:]

    def load_height(self, height: int) -> None:
        """Roll the working state back to a committed version
        (app.LoadHeight parity, app/app.go:729)."""
        for h, snap, _ in self._versions:
            if h == height:
                self._stores = {n: dict(d) for n, d in snap.items()}
                self._last_height = h
                # drop newer versions
                self._versions = [v for v in self._versions if v[0] <= height]
                return
        raise KeyError(f"no committed version at height {height}")

    def committed_hash(self, height: int) -> bytes:
        for h, _, ah in self._versions:
            if h == height:
                return ah
        raise KeyError(f"no committed version at height {height}")

    # --- export / import (genesis + snapshots) ----------------------------

    def export(self) -> Dict[str, Dict[str, str]]:
        """JSON-able dump of all stores (hex keys/values)."""
        return {
            n: {k.hex(): v.hex() for k, v in sorted(d.items())}
            for n, d in self._stores.items()
        }

    @classmethod
    def import_state(cls, dump: Dict[str, Dict[str, str]]) -> "MultiStore":
        ms = cls(sorted(dump))
        for n, d in dump.items():
            ms._stores[n] = {bytes.fromhex(k): bytes.fromhex(v) for k, v in d.items()}
        return ms
