"""x/bank equivalent: balances, transfers, module accounts, supply.

Parity role: cosmos-sdk bank keeper (fee deduction in the ante chain, mint
module provisioning, staking bonding — SURVEY.md §2.1).  Single native denom
``utia`` (appconsts.BondDenom).
"""

from __future__ import annotations

import hashlib
from typing import Dict

from celestia_tpu.state.store import KVStore

_BALANCE_PREFIX = b"bal/"
_SUPPLY_KEY = b"supply"


def module_address(name: str) -> bytes:
    """Deterministic address of a module account (fee collector, mint, bonded pool)."""
    return hashlib.sha256(b"module/" + name.encode()).digest()[:20]


FEE_COLLECTOR = module_address("fee_collector")
MINT_MODULE = module_address("mint")
BONDED_POOL = module_address("bonded_tokens_pool")
NOT_BONDED_POOL = module_address("not_bonded_tokens_pool")


class BankKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def balance(self, addr: bytes) -> int:
        raw = self.store.get(_BALANCE_PREFIX + addr)
        return int.from_bytes(raw, "big") if raw else 0

    def _set_balance(self, addr: bytes, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative balance")
        if amount == 0:
            self.store.delete(_BALANCE_PREFIX + addr)
        else:
            self.store.set(_BALANCE_PREFIX + addr, amount.to_bytes(16, "big"))

    def supply(self) -> int:
        raw = self.store.get(_SUPPLY_KEY)
        return int.from_bytes(raw, "big") if raw else 0

    def send(self, from_addr: bytes, to_addr: bytes, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative send amount")
        bal = self.balance(from_addr)
        if bal < amount:
            raise ValueError(
                f"insufficient funds: balance {bal}utia < {amount}utia"
            )
        self._set_balance(from_addr, bal - amount)
        self._set_balance(to_addr, self.balance(to_addr) + amount)

    def mint(self, to_addr: bytes, amount: int) -> None:
        """Create new supply (x/mint BeginBlocker provisioning)."""
        self._set_balance(to_addr, self.balance(to_addr) + amount)
        self.store.set(_SUPPLY_KEY, (self.supply() + amount).to_bytes(16, "big"))

    def burn(self, from_addr: bytes, amount: int) -> None:
        bal = self.balance(from_addr)
        if bal < amount:
            raise ValueError("insufficient funds to burn")
        self._set_balance(from_addr, bal - amount)
        self.store.set(_SUPPLY_KEY, (self.supply() - amount).to_bytes(16, "big"))

    def all_balances(self) -> Dict[bytes, int]:
        return {
            k[len(_BALANCE_PREFIX):]: int.from_bytes(v, "big")
            for k, v in self.store.iterate(_BALANCE_PREFIX)
        }

    # -- multi-denom (IBC vouchers) ------------------------------------
    #
    # The native denom rides the fast single-denom path above; other denoms
    # (ICS-20 voucher denoms on counterparty chains in tests — Celestia
    # itself never mints one thanks to x/tokenfilter) are stored under
    # denom-scoped keys.

    NATIVE_DENOM = "utia"

    def balance_of(self, addr: bytes, denom: str) -> int:
        if denom == self.NATIVE_DENOM:
            return self.balance(addr)
        raw = self.store.get(b"bal2/" + denom.encode() + b"/" + addr)
        return int.from_bytes(raw, "big") if raw else 0

    def _set_balance_of(self, addr: bytes, denom: str, amount: int) -> None:
        if denom == self.NATIVE_DENOM:
            self._set_balance(addr, amount)
            return
        if amount < 0:
            raise ValueError("negative balance")
        key = b"bal2/" + denom.encode() + b"/" + addr
        if amount == 0:
            self.store.delete(key)
        else:
            self.store.set(key, amount.to_bytes(16, "big"))

    def send_denom(
        self, from_addr: bytes, to_addr: bytes, amount: int, denom: str
    ) -> None:
        if denom == self.NATIVE_DENOM:
            self.send(from_addr, to_addr, amount)
            return
        bal = self.balance_of(from_addr, denom)
        if amount < 0 or bal < amount:
            raise ValueError(
                f"insufficient funds: balance {bal}{denom} < {amount}{denom}"
            )
        self._set_balance_of(from_addr, denom, bal - amount)
        self._set_balance_of(
            to_addr, denom, self.balance_of(to_addr, denom) + amount
        )

    def mint_denom(self, to_addr: bytes, amount: int, denom: str) -> None:
        if denom == self.NATIVE_DENOM:
            self.mint(to_addr, amount)
            return
        self._set_balance_of(
            to_addr, denom, self.balance_of(to_addr, denom) + amount
        )

    def burn_denom(self, from_addr: bytes, amount: int, denom: str) -> None:
        if denom == self.NATIVE_DENOM:
            self.burn(from_addr, amount)
            return
        bal = self.balance_of(from_addr, denom)
        if bal < amount:
            raise ValueError("insufficient funds to burn")
        self._set_balance_of(from_addr, denom, bal - amount)
