"""x/bank equivalent: balances, transfers, module accounts, supply, and
vesting-account lock enforcement.

Parity role: cosmos-sdk bank keeper (fee deduction in the ante chain, mint
module provisioning, staking bonding — SURVEY.md §2.1).  Single native denom
``utia`` (appconsts.BondDenom).

Vesting (auth/vesting parity): a vesting schedule stored against an address
locks part of its balance; `send` rejects spends of locked coins.  The
block time the locks are evaluated at is written INTO the bank store by the
App's BeginBlocker, so every branch (check state, ante branch, deliver
branch) sees the same deterministic clock — a wall-clock read here would
fork app hashes between validators.  Like the SDK, delegating locked coins
is allowed (sends to the bonded pool bypass the lock).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional, Tuple

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.store import KVStore

_BALANCE_PREFIX = b"bal/"
_SUPPLY_KEY = b"supply"
_VESTING_PREFIX = b"vest/"
_BLOCK_TIME_KEY = b"block_time_ns"


def module_address(name: str) -> bytes:
    """Deterministic address of a module account (fee collector, mint, bonded pool)."""
    return hashlib.sha256(b"module/" + name.encode()).digest()[:20]


FEE_COLLECTOR = module_address("fee_collector")
MINT_MODULE = module_address("mint")
BONDED_POOL = module_address("bonded_tokens_pool")
NOT_BONDED_POOL = module_address("not_bonded_tokens_pool")


class BankKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    @staticmethod
    def balance_key(addr: bytes) -> bytes:
        """The raw store key for an account balance — what a light client
        asks the `store/proof` query route to prove."""
        return _BALANCE_PREFIX + addr

    def balance(self, addr: bytes) -> int:
        raw = self.store.get(_BALANCE_PREFIX + addr)
        return int.from_bytes(raw, "big") if raw else 0

    def _set_balance(self, addr: bytes, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative balance")
        if amount == 0:
            self.store.delete(_BALANCE_PREFIX + addr)
        else:
            self.store.set(_BALANCE_PREFIX + addr, amount.to_bytes(16, "big"))

    def supply(self) -> int:
        raw = self.store.get(_SUPPLY_KEY)
        return int.from_bytes(raw, "big") if raw else 0

    def send(self, from_addr: bytes, to_addr: bytes, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative send amount")
        bal = self.balance(from_addr)
        if bal < amount:
            raise ValueError(
                f"insufficient funds: balance {bal}utia < {amount}utia"
            )
        if to_addr != BONDED_POOL:  # delegating locked coins is allowed
            locked = self.locked(from_addr)
            if bal - amount < locked:
                raise ValueError(
                    f"insufficient spendable funds: balance {bal}utia has "
                    f"{locked}utia still vesting"
                )
        self._set_balance(from_addr, bal - amount)
        self._set_balance(to_addr, self.balance(to_addr) + amount)

    def mint(self, to_addr: bytes, amount: int) -> None:
        """Create new supply (x/mint BeginBlocker provisioning)."""
        self._set_balance(to_addr, self.balance(to_addr) + amount)
        self.store.set(_SUPPLY_KEY, (self.supply() + amount).to_bytes(16, "big"))

    def burn(self, from_addr: bytes, amount: int) -> None:
        bal = self.balance(from_addr)
        if bal < amount:
            raise ValueError("insufficient funds to burn")
        self._set_balance(from_addr, bal - amount)
        self.store.set(_SUPPLY_KEY, (self.supply() - amount).to_bytes(16, "big"))

    def all_balances(self) -> Dict[bytes, int]:
        return {
            k[len(_BALANCE_PREFIX):]: int.from_bytes(v, "big")
            for k, v in self.store.iterate(_BALANCE_PREFIX)
        }

    # -- vesting accounts ----------------------------------------------
    #
    # schedule record: (original_vesting, start_ns, end_ns, delayed)
    # delayed=1: everything locked until end (DelayedVestingAccount);
    # delayed=0: linear release between start and end (ContinuousVesting).

    def set_block_time(self, now_ns: int) -> None:
        """Called by the App's BeginBlocker; the deterministic clock every
        lock evaluation uses."""
        self.store.set(_BLOCK_TIME_KEY, now_ns.to_bytes(8, "big"))

    def block_time(self) -> int:
        raw = self.store.get(_BLOCK_TIME_KEY)
        return int.from_bytes(raw, "big") if raw else 0

    def vesting_schedule(
        self, addr: bytes
    ) -> Optional[Tuple[int, int, int, bool]]:
        raw = self.store.get(_VESTING_PREFIX + addr)
        if raw is None:
            return None
        orig, pos = _read_varint(raw, 0)
        start, pos = _read_varint(raw, pos)
        end, pos = _read_varint(raw, pos)
        delayed, pos = _read_varint(raw, pos)
        return orig, start, end, bool(delayed)

    def set_vesting_schedule(
        self, addr: bytes, original: int, start_ns: int, end_ns: int,
        delayed: bool,
    ) -> None:
        if self.vesting_schedule(addr) is not None:
            raise ValueError("account already has a vesting schedule")
        if end_ns <= start_ns or original <= 0:
            raise ValueError("invalid vesting schedule")
        self.store.set(
            _VESTING_PREFIX + addr,
            bytes(
                _varint(original) + _varint(start_ns) + _varint(end_ns)
                + _varint(1 if delayed else 0)
            ),
        )

    def locked(self, addr: bytes) -> int:
        """Still-vesting amount at the current block time; fully-vested
        schedules are pruned on touch."""
        sched = self.vesting_schedule(addr)
        if sched is None:
            return 0
        original, start, end, delayed = sched
        now = self.block_time()
        if now >= end:
            self.store.delete(_VESTING_PREFIX + addr)
            return 0
        if delayed or now <= start:
            return original
        # continuous: linear release over [start, end]
        return original * (end - now) // (end - start)

    def spendable(self, addr: bytes) -> int:
        return max(0, self.balance(addr) - self.locked(addr))

    # -- multi-denom (IBC vouchers) ------------------------------------
    #
    # The native denom rides the fast single-denom path above; other denoms
    # (ICS-20 voucher denoms on counterparty chains in tests — Celestia
    # itself never mints one thanks to x/tokenfilter) are stored under
    # denom-scoped keys.

    NATIVE_DENOM = "utia"

    def balance_of(self, addr: bytes, denom: str) -> int:
        if denom == self.NATIVE_DENOM:
            return self.balance(addr)
        raw = self.store.get(b"bal2/" + denom.encode() + b"/" + addr)
        return int.from_bytes(raw, "big") if raw else 0

    def _set_balance_of(self, addr: bytes, denom: str, amount: int) -> None:
        if denom == self.NATIVE_DENOM:
            self._set_balance(addr, amount)
            return
        if amount < 0:
            raise ValueError("negative balance")
        key = b"bal2/" + denom.encode() + b"/" + addr
        if amount == 0:
            self.store.delete(key)
        else:
            self.store.set(key, amount.to_bytes(16, "big"))

    def send_denom(
        self, from_addr: bytes, to_addr: bytes, amount: int, denom: str
    ) -> None:
        if denom == self.NATIVE_DENOM:
            self.send(from_addr, to_addr, amount)
            return
        bal = self.balance_of(from_addr, denom)
        if amount < 0 or bal < amount:
            raise ValueError(
                f"insufficient funds: balance {bal}{denom} < {amount}{denom}"
            )
        self._set_balance_of(from_addr, denom, bal - amount)
        self._set_balance_of(
            to_addr, denom, self.balance_of(to_addr, denom) + amount
        )

    def mint_denom(self, to_addr: bytes, amount: int, denom: str) -> None:
        if denom == self.NATIVE_DENOM:
            self.mint(to_addr, amount)
            return
        self._set_balance_of(
            to_addr, denom, self.balance_of(to_addr, denom) + amount
        )

    def burn_denom(self, from_addr: bytes, amount: int, denom: str) -> None:
        if denom == self.NATIVE_DENOM:
            self.burn(from_addr, amount)
            return
        bal = self.balance_of(from_addr, denom)
        if bal < amount:
            raise ValueError("insufficient funds to burn")
        self._set_balance_of(from_addr, denom, bal - amount)
