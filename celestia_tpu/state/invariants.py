"""x/crisis equivalent: registered state invariants, assertable on demand.

Parity role: the cosmos-sdk crisis keeper the reference wires at
/root/reference/app/app.go:196,312-315 (CrisisKeeper + each module's
RegisterInvariants).  An invariant breach on a live chain is a
halt-the-world event; here `assert_invariants` raises InvariantBroken and
the node surfaces it.  MsgVerifyInvariant lets anyone force a check
on-chain (the SDK pays a constant fee for it; we charge gas).

The registered set mirrors the module invariants the reference's app
actually registers: bank total-supply, staking bonded-pool backing, and
distribution module-account solvency.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

GAS_COST_PER_INVARIANT = 100_000


class InvariantBroken(RuntimeError):
    pass


def bank_total_supply(app) -> Tuple[bool, str]:
    """Sum of all native-denom balances == recorded supply."""
    total = sum(app.bank.all_balances().values())
    supply = app.bank.supply()
    if total != supply:
        return False, f"sum(balances) {total} != supply {supply}"
    return True, ""


def staking_bonded_pool(app) -> Tuple[bool, str]:
    """Every validator's bonded tokens are backed 1:1 by the bonded pool
    module account."""
    from celestia_tpu.state.bank import BONDED_POOL

    bonded = sum(v.tokens for v in app.staking.validators())
    pool = app.bank.balance(BONDED_POOL)
    if bonded != pool:
        return False, f"validator tokens {bonded} != bonded pool {pool}"
    return True, ""


def distribution_solvency(app) -> Tuple[bool, str]:
    """The distribution module account covers the community pool and all
    accrued commission (outstanding delegator rewards ride the same
    account; solvency requires balance >= known liabilities)."""
    from celestia_tpu.state.modules.distribution import (
        _COMMISSION_PREFIX,
        DISTRIBUTION_MODULE,
    )

    liabilities = app.distribution.community_pool()
    for _, raw in app.distribution.store.iterate(_COMMISSION_PREFIX):
        liabilities += int.from_bytes(raw, "big")
    balance = app.bank.balance(DISTRIBUTION_MODULE)
    if balance < liabilities:
        return False, (
            f"distribution account {balance} < community pool + commission "
            f"{liabilities}"
        )
    return True, ""


def gov_deposits_escrowed(app) -> Tuple[bool, str]:
    """Proposals still in voting keep their deposits escrowed in the gov
    pool (refunded on resolution, burned on veto)."""
    from celestia_tpu.state.modules.gov import (
        GOV_MODULE_ADDR,
        PROPOSAL_STATUS_VOTING,
    )

    total = sum(
        p.deposit
        for p in app.gov.proposals()
        if p.status == PROPOSAL_STATUS_VOTING
    )
    balance = app.bank.balance(GOV_MODULE_ADDR)
    if balance < total:
        return False, f"gov escrow {balance} < active deposits {total}"
    return True, ""


DEFAULT_INVARIANTS: Dict[str, Callable] = {
    "bank/total-supply": bank_total_supply,
    "staking/bonded-pool": staking_bonded_pool,
    "distribution/solvency": distribution_solvency,
    "gov/deposits": gov_deposits_escrowed,
}


def assert_invariants(app, names: List[str] = None) -> Dict[str, str]:
    """Run all (or the named) registered invariants; raise InvariantBroken
    on the first failure.  Returns {name: 'ok'} on success.  An unknown
    name is an error — silently checking nothing would report success for
    a check that never ran (the SDK errors on unknown routes too)."""
    if names:
        unknown = [n for n in names if n not in DEFAULT_INVARIANTS]
        if unknown:
            raise ValueError(f"unknown invariant route(s): {unknown}")
    results: Dict[str, str] = {}
    for name, fn in DEFAULT_INVARIANTS.items():
        if names and name not in names:
            continue
        ok, msg = fn(app)
        if not ok:
            raise InvariantBroken(f"invariant {name} broken: {msg}")
        results[name] = "ok"
    return results
