"""Parameter store with subspaces + the hardfork-param governance blocklist.

Parity role: cosmos params subspaces as used by every module, plus
x/paramfilter's ParamBlockList (gov_handler.go:36-60) enforcing that
hardfork-only parameters (the list at /root/reference/app/app.go:856-867)
cannot be changed by governance.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple

from celestia_tpu.appconsts import (
    DEFAULT_GAS_PER_BLOB_BYTE,
    DEFAULT_GOV_MAX_SQUARE_SIZE,
    DEFAULT_UNBONDING_TIME_SECONDS,
    GLOBAL_MIN_GAS_PRICE_PPM,
)
from celestia_tpu.state.store import KVStore


_ABSENT = object()  # memoized "key not in store" (distinct from stored null)


class ParamsKeeper:
    def __init__(self, store: KVStore):
        self.store = store
        # read-through memo: the ante chain reads the same few params for
        # every tx in a proposal; decode each once per keeper instance.
        # Writes go through set() (which invalidates), and every branch
        # swap builds a fresh keeper, so the memo cannot go stale.
        self._memo: Dict[Tuple[str, str], Any] = {}

    def _key(self, subspace: str, key: str) -> bytes:
        return f"{subspace}/{key}".encode()

    def set(self, subspace: str, key: str, value: Any) -> None:
        self.store.set(self._key(subspace, key), json.dumps(value).encode())
        self._memo.pop((subspace, key), None)

    def get(self, subspace: str, key: str, default: Any = None) -> Any:
        mk = (subspace, key)
        if mk in self._memo:
            val = self._memo[mk]
        else:
            raw = self.store.get(self._key(subspace, key))
            val = _ABSENT if raw is None else json.loads(raw.decode())
            self._memo[mk] = val
        if val is _ABSENT:
            return default
        if isinstance(val, (list, dict)):
            # callers may mutate their copy; the memo (and therefore
            # later reads) must keep matching the committed store
            return json.loads(json.dumps(val))
        return val

    def has(self, subspace: str, key: str) -> bool:
        return self.store.has(self._key(subspace, key))

    def all_params(self) -> Dict[str, Any]:
        return {k.decode(): json.loads(v.decode()) for k, v in self.store.iterate()}


# (subspace, key) pairs changeable only via hardfork — app.go:856-867 parity.
BLOCKED_PARAMS: Tuple[Tuple[str, str], ...] = (
    ("bank", "SendEnabled"),
    ("staking", "BondDenom"),
    ("staking", "MaxValidators"),
    ("staking", "UnbondingTime"),
    ("consensus", "ValidatorPubKeyTypes"),
)


class ParamBlockList:
    """x/paramfilter: rejects governance changes to blocked params."""

    def __init__(self, blocked: Tuple[Tuple[str, str], ...] = BLOCKED_PARAMS):
        self.blocked = set(blocked)

    def is_blocked(self, subspace: str, key: str) -> bool:
        return (subspace, key) in self.blocked

    def validate_change(self, subspace: str, key: str) -> None:
        if self.is_blocked(subspace, key):
            raise ValueError(
                f"parameter {subspace}/{key} can only be changed via hardfork"
            )


def set_default_params(params: ParamsKeeper) -> None:
    """Genesis defaults (initial_consts.go:8-31, v2/app_consts.go:5-9,
    x/blob params at x/blob keeper defaults)."""
    params.set("blob", "GasPerBlobByte", DEFAULT_GAS_PER_BLOB_BYTE)
    params.set("blob", "GovMaxSquareSize", DEFAULT_GOV_MAX_SQUARE_SIZE)
    params.set("minfee", "NetworkMinGasPricePpm", GLOBAL_MIN_GAS_PRICE_PPM)
    params.set("staking", "BondDenom", "utia")
    params.set("staking", "UnbondingTime", DEFAULT_UNBONDING_TIME_SECONDS)
    params.set("staking", "MaxValidators", 100)
    params.set("bank", "SendEnabled", True)
    params.set("consensus", "ValidatorPubKeyTypes", ["secp256k1"])
    params.set("blobstream", "DataCommitmentWindow", 400)
