"""Transaction codec: messages, fees, sign bytes, signatures.

This framework's equivalent of the reference's SDK tx layer
(app/encoding/encoding.go MakeConfig + SIGN_MODE_DIRECT signing used by
pkg/user/signer.go:507-562).  Wire format is a deterministic length-prefixed
binary encoding (not protobuf — one canonical byte representation, no
map/ordering pitfalls); sign bytes cover body + auth info + chain id, so
fee, gas, sequence and chain are all signature-protected.

Message set mirrors the reference's state-machine surface (SURVEY.md §2.1):
bank send, x/blob MsgPayForBlobs, x/upgrade signal/try-upgrade, x/blobstream
EVM-address registration, staking delegate/undelegate, and a gov-gated param
change (x/paramfilter's enforcement point).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from celestia_tpu.da.namespace import Namespace
from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.utils.secp256k1 import (
    MULTISIG_PREFIX,
    MultisigPubKey,
    PrivateKey,
    PublicKey,
)

ADDRESS_SIZE = 20


def _put_bytes(out: bytearray, b: bytes):
    out += _varint(len(b))
    out += b


def _get_bytes(raw: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = _read_varint(raw, pos)
    if pos + n > len(raw):
        raise ValueError("truncated bytes field")
    return raw[pos : pos + n], pos + n


# ---------------------------------------------------------------------------
# Messages
# ---------------------------------------------------------------------------


@dataclass
class SubmitResult:
    """Outcome of a tx broadcast (the BroadcastTx RPC surface).  Lives
    here — not in client/signer.py where it grew up — because the node
    tier PRODUCES it (testnode broadcast, network replication) and the
    client tier consumes it: state/ is the layer both may import
    (celint R8)."""

    code: int
    log: str
    tx_hash: bytes
    height: Optional[int] = None


@dataclass(frozen=True)
class MsgSend:
    """x/bank transfer (the reference's most common non-blob tx)."""

    from_addr: bytes
    to_addr: bytes
    amount: int  # utia

    TYPE = 1

    def signers(self) -> List[bytes]:
        return [self.from_addr]


@dataclass(frozen=True)
class MsgPayForBlobs:
    """x/blob MsgPayForBlobs (x/blob/types/payforblob.go:49-146 parity):
    pays for blob inclusion; blobs themselves never touch state."""

    signer: bytes
    namespaces: Tuple[bytes, ...]  # 29-byte raw namespaces
    blob_sizes: Tuple[int, ...]
    share_commitments: Tuple[bytes, ...]  # 32-byte commitments
    share_versions: Tuple[int, ...]

    TYPE = 2

    def signers(self) -> List[bytes]:
        return [self.signer]


@dataclass(frozen=True)
class MsgSignalVersion:
    """x/upgrade: validator signals readiness for an app version."""

    validator: bytes
    version: int

    TYPE = 3

    def signers(self) -> List[bytes]:
        return [self.validator]


@dataclass(frozen=True)
class MsgTryUpgrade:
    """x/upgrade: tally signals; upgrade if >= 5/6 of power signalled."""

    signer: bytes

    TYPE = 4

    def signers(self) -> List[bytes]:
        return [self.signer]


@dataclass(frozen=True)
class MsgRegisterEVMAddress:
    """x/blobstream: validator registers its EVM orchestrator address."""

    validator: bytes
    evm_address: bytes  # 20 bytes

    TYPE = 5

    def signers(self) -> List[bytes]:
        return [self.validator]


@dataclass(frozen=True)
class MsgDelegate:
    delegator: bytes
    validator: bytes
    amount: int

    TYPE = 6

    def signers(self) -> List[bytes]:
        return [self.delegator]


@dataclass(frozen=True)
class MsgUndelegate:
    delegator: bytes
    validator: bytes
    amount: int

    TYPE = 7

    def signers(self) -> List[bytes]:
        return [self.delegator]


@dataclass(frozen=True)
class MsgParamChange:
    """Governance parameter change.  The executing authority MUST be the gov
    module account (GOV_MODULE_ADDR) — params are only writable through a
    passed proposal, never by a user-signed message
    (x/paramfilter/gov_handler.go:36-60: the reference exposes param changes
    exclusively through the gov proposal route).  x/paramfilter additionally
    blocks hardfork-only params."""

    authority: bytes
    subspace: str
    key: str
    value: bytes

    TYPE = 8

    def signers(self) -> List[bytes]:
        return [self.authority]


@dataclass(frozen=True)
class MsgSubmitProposal:
    """Submit a governance proposal: param changes (ParamChangeProposal,
    executed through the blocklist-gated handler,
    x/paramfilter/gov_handler.go:36-60) and/or a community-pool spend
    (distribution CommunityPoolSpendProposal)."""

    proposer: bytes
    title: str
    description: str
    # each change: (subspace, key, json-encoded value)
    changes: Tuple[Tuple[str, str, bytes], ...]
    deposit: int
    # community-pool spend (0 amount = none)
    spend_to: bytes = b""
    spend_amount: int = 0

    TYPE = 9

    def signers(self) -> List[bytes]:
        return [self.proposer]


@dataclass(frozen=True)
class MsgVote:
    """Vote on an active governance proposal (x/gov vote)."""

    voter: bytes
    proposal_id: int
    option: int  # 1 = yes, 2 = no, 3 = abstain, 4 = no-with-veto

    TYPE = 10

    OPTION_YES = 1
    OPTION_NO = 2
    OPTION_ABSTAIN = 3
    OPTION_VETO = 4

    def signers(self) -> List[bytes]:
        return [self.voter]


@dataclass(frozen=True)
class MsgGrantAllowance:
    """x/feegrant: grant a fee allowance (basic or periodic)."""

    granter: bytes
    grantee: bytes
    kind: int  # feegrant.KIND_BASIC / KIND_PERIODIC
    spend_limit: int  # 0 = unlimited
    expiration_ns: int  # 0 = never
    period_ns: int = 0
    period_spend_limit: int = 0

    TYPE = 11

    def signers(self) -> List[bytes]:
        return [self.granter]


@dataclass(frozen=True)
class MsgRevokeAllowance:
    """x/feegrant: revoke a fee allowance."""

    granter: bytes
    grantee: bytes

    TYPE = 12

    def signers(self) -> List[bytes]:
        return [self.granter]


@dataclass(frozen=True)
class MsgAuthzGrant:
    """x/authz: authorize a grantee to execute a message type."""

    granter: bytes
    grantee: bytes
    msg_type: int  # Msg.TYPE id of the authorized message
    spend_limit: int  # 0 = unlimited (generic authorization)
    expiration_ns: int  # 0 = never

    TYPE = 13

    def signers(self) -> List[bytes]:
        return [self.granter]


@dataclass(frozen=True)
class MsgAuthzRevoke:
    """x/authz: revoke an authorization."""

    granter: bytes
    grantee: bytes
    msg_type: int

    TYPE = 14

    def signers(self) -> List[bytes]:
        return [self.granter]


@dataclass(frozen=True)
class MsgExec:
    """x/authz: execute wrapped messages under existing grants.  The tx is
    signed by the grantee; each inner message's declared signer must have
    granted the matching authorization."""

    grantee: bytes
    inner: Tuple["Msg", ...]

    TYPE = 15

    def signers(self) -> List[bytes]:
        return [self.grantee]


@dataclass(frozen=True)
class MsgWithdrawDelegatorReward:
    """x/distribution: withdraw accrued delegation rewards."""

    delegator: bytes
    validator: bytes

    TYPE = 16

    def signers(self) -> List[bytes]:
        return [self.delegator]


@dataclass(frozen=True)
class MsgWithdrawValidatorCommission:
    """x/distribution: withdraw a validator's accrued commission."""

    validator: bytes

    TYPE = 17

    def signers(self) -> List[bytes]:
        return [self.validator]


@dataclass(frozen=True)
class MsgFundCommunityPool:
    """x/distribution: move own funds into the community pool."""

    depositor: bytes
    amount: int

    TYPE = 18

    def signers(self) -> List[bytes]:
        return [self.depositor]


@dataclass(frozen=True)
class MsgSetWithdrawAddress:
    """x/distribution: set the address rewards are withdrawn to."""

    delegator: bytes
    withdraw_address: bytes

    TYPE = 19

    def signers(self) -> List[bytes]:
        return [self.delegator]


@dataclass(frozen=True)
class MsgUnjail:
    """x/slashing: a jailed validator rejoins after its jail duration."""

    validator: bytes

    TYPE = 20

    def signers(self) -> List[bytes]:
        return [self.validator]


@dataclass(frozen=True)
class MsgSubmitEvidence:
    """x/evidence: submit equivocation (double-sign) evidence.  Carries the
    two conflicting signed votes — the evidence must prove itself (the
    msg path is open to anyone, unlike comet's pre-verified stream)."""

    submitter: bytes
    validator: bytes
    height: int
    time_ns: int
    block_hash_a: bytes = b""
    sig_a: bytes = b""
    block_hash_b: bytes = b""
    sig_b: bytes = b""

    TYPE = 21

    def signers(self) -> List[bytes]:
        return [self.submitter]


@dataclass(frozen=True)
class MsgVerifyInvariant:
    """x/crisis: force an on-chain invariant check (empty route = all)."""

    sender: bytes
    invariant: str = ""

    TYPE = 22

    def signers(self) -> List[bytes]:
        return [self.sender]


@dataclass(frozen=True)
class MsgCreateVestingAccount:
    """auth/vesting: fund a new account under a vesting schedule
    (continuous by default; delayed locks everything until end_time)."""

    from_addr: bytes
    to_addr: bytes
    amount: int
    end_time_ns: int
    delayed: bool = False

    TYPE = 23

    def signers(self) -> List[bytes]:
        return [self.from_addr]


Msg = Union[
    MsgSend,
    MsgPayForBlobs,
    MsgSignalVersion,
    MsgTryUpgrade,
    MsgRegisterEVMAddress,
    MsgDelegate,
    MsgUndelegate,
    MsgParamChange,
    MsgSubmitProposal,
    MsgVote,
    MsgGrantAllowance,
    MsgRevokeAllowance,
    MsgAuthzGrant,
    MsgAuthzRevoke,
    MsgExec,
    MsgWithdrawDelegatorReward,
    MsgWithdrawValidatorCommission,
    MsgFundCommunityPool,
    MsgSetWithdrawAddress,
    MsgUnjail,
    MsgSubmitEvidence,
    MsgVerifyInvariant,
    MsgCreateVestingAccount,
]

_MSG_TYPES = {
    cls.TYPE: cls
    for cls in (
        MsgSend,
        MsgPayForBlobs,
        MsgSignalVersion,
        MsgTryUpgrade,
        MsgRegisterEVMAddress,
        MsgDelegate,
        MsgUndelegate,
        MsgParamChange,
        MsgSubmitProposal,
        MsgVote,
        MsgGrantAllowance,
        MsgRevokeAllowance,
        MsgAuthzGrant,
        MsgAuthzRevoke,
        MsgExec,
        MsgWithdrawDelegatorReward,
        MsgWithdrawValidatorCommission,
        MsgFundCommunityPool,
        MsgSetWithdrawAddress,
        MsgUnjail,
        MsgSubmitEvidence,
        MsgVerifyInvariant,
        MsgCreateVestingAccount,
    )
}


def marshal_msg(msg: Msg) -> bytes:
    out = bytearray()
    out += _varint(msg.TYPE)
    if isinstance(msg, MsgSend):
        _put_bytes(out, msg.from_addr)
        _put_bytes(out, msg.to_addr)
        out += _varint(msg.amount)
    elif isinstance(msg, MsgPayForBlobs):
        _put_bytes(out, msg.signer)
        out += _varint(len(msg.namespaces))
        for ns, size, comm, ver in zip(
            msg.namespaces, msg.blob_sizes, msg.share_commitments, msg.share_versions
        ):
            _put_bytes(out, ns)
            out += _varint(size)
            _put_bytes(out, comm)
            out += _varint(ver)
    elif isinstance(msg, MsgSignalVersion):
        _put_bytes(out, msg.validator)
        out += _varint(msg.version)
    elif isinstance(msg, MsgTryUpgrade):
        _put_bytes(out, msg.signer)
    elif isinstance(msg, MsgRegisterEVMAddress):
        _put_bytes(out, msg.validator)
        _put_bytes(out, msg.evm_address)
    elif isinstance(msg, (MsgDelegate, MsgUndelegate)):
        _put_bytes(out, msg.delegator)
        _put_bytes(out, msg.validator)
        out += _varint(msg.amount)
    elif isinstance(msg, MsgParamChange):
        _put_bytes(out, msg.authority)
        _put_bytes(out, msg.subspace.encode())
        _put_bytes(out, msg.key.encode())
        _put_bytes(out, msg.value)
    elif isinstance(msg, MsgSubmitProposal):
        _put_bytes(out, msg.proposer)
        _put_bytes(out, msg.title.encode())
        _put_bytes(out, msg.description.encode())
        out += _varint(len(msg.changes))
        for sub, key, val in msg.changes:
            _put_bytes(out, sub.encode())
            _put_bytes(out, key.encode())
            _put_bytes(out, val)
        out += _varint(msg.deposit)
        _put_bytes(out, msg.spend_to)
        out += _varint(msg.spend_amount)
    elif isinstance(msg, MsgVote):
        _put_bytes(out, msg.voter)
        out += _varint(msg.proposal_id)
        out += _varint(msg.option)
    elif isinstance(msg, MsgGrantAllowance):
        _put_bytes(out, msg.granter)
        _put_bytes(out, msg.grantee)
        out += _varint(msg.kind)
        out += _varint(msg.spend_limit)
        out += _varint(msg.expiration_ns)
        out += _varint(msg.period_ns)
        out += _varint(msg.period_spend_limit)
    elif isinstance(msg, MsgRevokeAllowance):
        _put_bytes(out, msg.granter)
        _put_bytes(out, msg.grantee)
    elif isinstance(msg, MsgAuthzGrant):
        _put_bytes(out, msg.granter)
        _put_bytes(out, msg.grantee)
        out += _varint(msg.msg_type)
        out += _varint(msg.spend_limit)
        out += _varint(msg.expiration_ns)
    elif isinstance(msg, MsgAuthzRevoke):
        _put_bytes(out, msg.granter)
        _put_bytes(out, msg.grantee)
        out += _varint(msg.msg_type)
    elif isinstance(msg, MsgExec):
        _put_bytes(out, msg.grantee)
        out += _varint(len(msg.inner))
        for im in msg.inner:
            _put_bytes(out, marshal_msg(im))
    elif isinstance(msg, MsgWithdrawDelegatorReward):
        _put_bytes(out, msg.delegator)
        _put_bytes(out, msg.validator)
    elif isinstance(msg, MsgWithdrawValidatorCommission):
        _put_bytes(out, msg.validator)
    elif isinstance(msg, MsgFundCommunityPool):
        _put_bytes(out, msg.depositor)
        out += _varint(msg.amount)
    elif isinstance(msg, MsgSetWithdrawAddress):
        _put_bytes(out, msg.delegator)
        _put_bytes(out, msg.withdraw_address)
    elif isinstance(msg, MsgUnjail):
        _put_bytes(out, msg.validator)
    elif isinstance(msg, MsgSubmitEvidence):
        _put_bytes(out, msg.submitter)
        _put_bytes(out, msg.validator)
        out += _varint(msg.height)
        out += _varint(msg.time_ns)
        _put_bytes(out, msg.block_hash_a)
        _put_bytes(out, msg.sig_a)
        _put_bytes(out, msg.block_hash_b)
        _put_bytes(out, msg.sig_b)
    elif isinstance(msg, MsgVerifyInvariant):
        _put_bytes(out, msg.sender)
        _put_bytes(out, msg.invariant.encode())
    elif isinstance(msg, MsgCreateVestingAccount):
        _put_bytes(out, msg.from_addr)
        _put_bytes(out, msg.to_addr)
        out += _varint(msg.amount)
        out += _varint(msg.end_time_ns)
        out += _varint(1 if msg.delayed else 0)
    else:
        raise TypeError(f"unknown msg type {type(msg)}")
    return bytes(out)


def unmarshal_msg(raw: bytes, pos: int = 0) -> Tuple[Msg, int]:
    t, pos = _read_varint(raw, pos)
    if t == MsgSend.TYPE:
        frm, pos = _get_bytes(raw, pos)
        to, pos = _get_bytes(raw, pos)
        amt, pos = _read_varint(raw, pos)
        return MsgSend(frm, to, amt), pos
    if t == MsgPayForBlobs.TYPE:
        signer, pos = _get_bytes(raw, pos)
        n, pos = _read_varint(raw, pos)
        nss, sizes, comms, vers = [], [], [], []
        for _ in range(n):
            ns, pos = _get_bytes(raw, pos)
            size, pos = _read_varint(raw, pos)
            comm, pos = _get_bytes(raw, pos)
            ver, pos = _read_varint(raw, pos)
            nss.append(ns)
            sizes.append(size)
            comms.append(comm)
            vers.append(ver)
        return (
            MsgPayForBlobs(
                signer, tuple(nss), tuple(sizes), tuple(comms), tuple(vers)
            ),
            pos,
        )
    if t == MsgSignalVersion.TYPE:
        val, pos = _get_bytes(raw, pos)
        ver, pos = _read_varint(raw, pos)
        return MsgSignalVersion(val, ver), pos
    if t == MsgTryUpgrade.TYPE:
        signer, pos = _get_bytes(raw, pos)
        return MsgTryUpgrade(signer), pos
    if t == MsgRegisterEVMAddress.TYPE:
        val, pos = _get_bytes(raw, pos)
        evm, pos = _get_bytes(raw, pos)
        return MsgRegisterEVMAddress(val, evm), pos
    if t in (MsgDelegate.TYPE, MsgUndelegate.TYPE):
        d, pos = _get_bytes(raw, pos)
        v, pos = _get_bytes(raw, pos)
        amt, pos = _read_varint(raw, pos)
        cls = MsgDelegate if t == MsgDelegate.TYPE else MsgUndelegate
        return cls(d, v, amt), pos
    if t == MsgParamChange.TYPE:
        auth, pos = _get_bytes(raw, pos)
        sub, pos = _get_bytes(raw, pos)
        key, pos = _get_bytes(raw, pos)
        val, pos = _get_bytes(raw, pos)
        return MsgParamChange(auth, sub.decode(), key.decode(), val), pos
    if t == MsgSubmitProposal.TYPE:
        proposer, pos = _get_bytes(raw, pos)
        title, pos = _get_bytes(raw, pos)
        desc, pos = _get_bytes(raw, pos)
        n, pos = _read_varint(raw, pos)
        changes = []
        for _ in range(n):
            sub, pos = _get_bytes(raw, pos)
            key, pos = _get_bytes(raw, pos)
            val, pos = _get_bytes(raw, pos)
            changes.append((sub.decode(), key.decode(), val))
        deposit, pos = _read_varint(raw, pos)
        spend_to, pos = _get_bytes(raw, pos)
        spend_amount, pos = _read_varint(raw, pos)
        return (
            MsgSubmitProposal(
                proposer, title.decode(), desc.decode(), tuple(changes),
                deposit, spend_to, spend_amount,
            ),
            pos,
        )
    if t == MsgVote.TYPE:
        voter, pos = _get_bytes(raw, pos)
        pid, pos = _read_varint(raw, pos)
        opt, pos = _read_varint(raw, pos)
        return MsgVote(voter, pid, opt), pos
    if t == MsgGrantAllowance.TYPE:
        granter, pos = _get_bytes(raw, pos)
        grantee, pos = _get_bytes(raw, pos)
        kind, pos = _read_varint(raw, pos)
        spend, pos = _read_varint(raw, pos)
        exp, pos = _read_varint(raw, pos)
        pns, pos = _read_varint(raw, pos)
        plim, pos = _read_varint(raw, pos)
        return MsgGrantAllowance(granter, grantee, kind, spend, exp, pns, plim), pos
    if t == MsgRevokeAllowance.TYPE:
        granter, pos = _get_bytes(raw, pos)
        grantee, pos = _get_bytes(raw, pos)
        return MsgRevokeAllowance(granter, grantee), pos
    if t == MsgAuthzGrant.TYPE:
        granter, pos = _get_bytes(raw, pos)
        grantee, pos = _get_bytes(raw, pos)
        mt, pos = _read_varint(raw, pos)
        spend, pos = _read_varint(raw, pos)
        exp, pos = _read_varint(raw, pos)
        return MsgAuthzGrant(granter, grantee, mt, spend, exp), pos
    if t == MsgAuthzRevoke.TYPE:
        granter, pos = _get_bytes(raw, pos)
        grantee, pos = _get_bytes(raw, pos)
        mt, pos = _read_varint(raw, pos)
        return MsgAuthzRevoke(granter, grantee, mt), pos
    if t == MsgExec.TYPE:
        grantee, pos = _get_bytes(raw, pos)
        n, pos = _read_varint(raw, pos)
        if n > 32:
            raise ValueError("MsgExec carries too many inner messages")
        inner = []
        for _ in range(n):
            imraw, pos = _get_bytes(raw, pos)
            im, used = unmarshal_msg(imraw)
            if used != len(imraw):
                raise ValueError("trailing bytes in MsgExec inner msg")
            if isinstance(im, MsgExec):
                raise ValueError("nested MsgExec is not allowed")
            inner.append(im)
        return MsgExec(grantee, tuple(inner)), pos
    if t == MsgWithdrawDelegatorReward.TYPE:
        d, pos = _get_bytes(raw, pos)
        v, pos = _get_bytes(raw, pos)
        return MsgWithdrawDelegatorReward(d, v), pos
    if t == MsgWithdrawValidatorCommission.TYPE:
        v, pos = _get_bytes(raw, pos)
        return MsgWithdrawValidatorCommission(v), pos
    if t == MsgFundCommunityPool.TYPE:
        d, pos = _get_bytes(raw, pos)
        amt, pos = _read_varint(raw, pos)
        return MsgFundCommunityPool(d, amt), pos
    if t == MsgSetWithdrawAddress.TYPE:
        d, pos = _get_bytes(raw, pos)
        wa, pos = _get_bytes(raw, pos)
        return MsgSetWithdrawAddress(d, wa), pos
    if t == MsgUnjail.TYPE:
        v, pos = _get_bytes(raw, pos)
        return MsgUnjail(v), pos
    if t == MsgSubmitEvidence.TYPE:
        s, pos = _get_bytes(raw, pos)
        v, pos = _get_bytes(raw, pos)
        h, pos = _read_varint(raw, pos)
        tns, pos = _read_varint(raw, pos)
        bha, pos = _get_bytes(raw, pos)
        siga, pos = _get_bytes(raw, pos)
        bhb, pos = _get_bytes(raw, pos)
        sigb, pos = _get_bytes(raw, pos)
        return MsgSubmitEvidence(s, v, h, tns, bha, siga, bhb, sigb), pos
    if t == MsgVerifyInvariant.TYPE:
        s, pos = _get_bytes(raw, pos)
        inv, pos = _get_bytes(raw, pos)
        return MsgVerifyInvariant(s, inv.decode()), pos
    if t == MsgCreateVestingAccount.TYPE:
        f, pos = _get_bytes(raw, pos)
        to, pos = _get_bytes(raw, pos)
        amt, pos = _read_varint(raw, pos)
        end, pos = _read_varint(raw, pos)
        delayed, pos = _read_varint(raw, pos)
        return MsgCreateVestingAccount(f, to, amt, end, bool(delayed)), pos
    raise ValueError(f"unknown msg type id {t}")


# ---------------------------------------------------------------------------
# Tx
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Fee:
    amount: int  # utia
    gas_limit: int

    def gas_price(self) -> float:
        return self.amount / self.gas_limit if self.gas_limit else 0.0


@dataclass(frozen=True)
class Tx:
    msgs: Tuple[Msg, ...]
    fee: Fee
    pubkey: bytes  # 33-byte compressed secp256k1
    sequence: int
    account_number: int
    memo: str = ""
    signature: bytes = b""
    # reject inclusion above this height; 0 = no timeout (the SDK's
    # TxTimeoutHeightDecorator field)
    timeout_height: int = 0
    # x/feegrant: when set, this address's allowance pays the fee instead
    # of the signer (SDK Fee.granter; covered by the signature)
    fee_granter: bytes = b""

    def body_bytes(self) -> bytes:
        out = bytearray()
        out += _varint(len(self.msgs))
        for m in self.msgs:
            _put_bytes(out, marshal_msg(m))
        _put_bytes(out, self.memo.encode())
        out += _varint(self.timeout_height)
        return bytes(out)

    def auth_bytes(self) -> bytes:
        out = bytearray()
        out += _varint(self.fee.amount)
        out += _varint(self.fee.gas_limit)
        _put_bytes(out, self.pubkey)
        out += _varint(self.sequence)
        out += _varint(self.account_number)
        _put_bytes(out, self.fee_granter)
        return bytes(out)

    def sign_bytes(self, chain_id: str) -> bytes:
        # memoized: decoded Tx objects are cached across CheckTx/Prepare/
        # Process (state/app.py _decoded_cache), and each stage re-derives
        # the same digest; the object is immutable so the digest is too
        cached = getattr(self, "_sign_bytes_memo", None)
        if cached is not None and cached[0] == chain_id:
            return cached[1]
        out = bytearray()
        _put_bytes(out, chain_id.encode())
        # decoded txs carry their verbatim wire slices (unmarshal_tx);
        # locally-built txs serialize fresh — identical bytes either way
        # because the wire is canonical (minimal varints enforced by
        # _read_varint), and the raw slices are what the signature
        # actually covers (SignDoc parity)
        body = getattr(self, "_wire_body", None)
        auth = getattr(self, "_wire_auth", None)
        _put_bytes(out, body if body is not None else self.body_bytes())
        _put_bytes(out, auth if auth is not None else self.auth_bytes())
        digest = hashlib.sha256(bytes(out)).digest()
        object.__setattr__(self, "_sign_bytes_memo", (chain_id, digest))
        return digest

    def signed(self, priv: PrivateKey, chain_id: str) -> "Tx":
        sig = priv.sign(self.sign_bytes(chain_id))
        return Tx(
            self.msgs, self.fee, self.pubkey, self.sequence,
            self.account_number, self.memo, sig, self.timeout_height,
            self.fee_granter,
        )

    def is_multisig(self) -> bool:
        return bool(self.pubkey) and self.pubkey[0] == MULTISIG_PREFIX

    def verify_signature(self, chain_id: str) -> bool:
        msg = self.sign_bytes(chain_id)
        if self.is_multisig():
            try:
                mk = MultisigPubKey.unmarshal(self.pubkey)
            except ValueError:
                return False
            return mk.verify(msg, self.signature)
        try:
            pk = PublicKey.from_compressed(self.pubkey)
        except ValueError:
            return False
        return pk.verify(msg, self.signature)

    def signer_address(self) -> bytes:
        # memoized: the ante chain derives the signer several times per
        # tx and decoded txs are cached across admission/filter passes
        # (idempotent, so benign under concurrent first calls)
        memo = self.__dict__.get("_signer_addr")
        if memo is None:
            if self.is_multisig():
                memo = MultisigPubKey.unmarshal(self.pubkey).address()
            else:
                memo = PublicKey.from_compressed(self.pubkey).address()
            object.__setattr__(self, "_signer_addr", memo)
        return memo

    def marshal(self) -> bytes:
        out = bytearray()
        _put_bytes(out, self.body_bytes())
        _put_bytes(out, self.auth_bytes())
        _put_bytes(out, self.signature)
        return bytes(out)

    def hash(self) -> bytes:
        return hashlib.sha256(self.marshal()).digest()


def unmarshal_tx(raw: bytes) -> Tx:
    body, pos = _get_bytes(raw, 0)
    auth, pos = _get_bytes(raw, pos)
    sig, pos = _get_bytes(raw, pos)
    if pos != len(raw):
        raise ValueError("trailing bytes after tx")
    # body
    bpos = 0
    n_msgs, bpos = _read_varint(body, bpos)
    msgs = []
    for _ in range(n_msgs):
        mraw, bpos = _get_bytes(body, bpos)
        msg, used = unmarshal_msg(mraw)
        if used != len(mraw):
            raise ValueError("trailing bytes in msg")
        msgs.append(msg)
    memo_b, bpos = _get_bytes(body, bpos)
    timeout_height, bpos = _read_varint(body, bpos)
    if bpos != len(body):
        raise ValueError("trailing bytes in tx body")
    # auth
    apos = 0
    fee_amount, apos = _read_varint(auth, apos)
    gas_limit, apos = _read_varint(auth, apos)
    pubkey, apos = _get_bytes(auth, apos)
    sequence, apos = _read_varint(auth, apos)
    account_number, apos = _read_varint(auth, apos)
    fee_granter, apos = _get_bytes(auth, apos)
    if apos != len(auth):
        raise ValueError("trailing bytes in tx auth")
    tx = Tx(
        tuple(msgs), Fee(fee_amount, gas_limit), pubkey, sequence,
        account_number, memo_b.decode(), sig, timeout_height, fee_granter,
    )
    # stash the verbatim wire slices: sign_bytes hashes THESE instead of
    # re-serializing (SignDoc semantics — the reference signs over the
    # raw BodyBytes/AuthInfoBytes from the wire, and re-encoding 512
    # proposal txs was a visible slice of FilterTxs host time)
    object.__setattr__(tx, "_wire_body", body)
    object.__setattr__(tx, "_wire_auth", auth)
    return tx
