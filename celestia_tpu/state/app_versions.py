"""Versioned module manager (ADR-022 / app/module parity).

The reference registers each module with a [FromVersion, ToVersion] range in
the manager (app/module/module.go:20-100 NewManager + VersionedModule), a
versioned configurator records which messages each app version accepts
(configurator.go:34-76, consumed by the ante MsgVersioningGateKeeper), and
RunMigrations (module.go:231) walks registered per-module migrations on
upgrade.  This file implements the same structure: modules declare their
version range, owned message types and migrations; everything else —
accepted-message sets, supported versions, migration plans — is DERIVED
from the module registry rather than hand-kept tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from celestia_tpu.appconsts import V1_VERSION, V2_VERSION
from celestia_tpu.state.tx import (
    MsgAuthzGrant,
    MsgAuthzRevoke,
    MsgCreateVestingAccount,
    MsgDelegate,
    MsgExec,
    MsgFundCommunityPool,
    MsgGrantAllowance,
    MsgParamChange,
    MsgPayForBlobs,
    MsgRegisterEVMAddress,
    MsgRevokeAllowance,
    MsgSend,
    MsgSetWithdrawAddress,
    MsgSignalVersion,
    MsgSubmitEvidence,
    MsgSubmitProposal,
    MsgTryUpgrade,
    MsgUndelegate,
    MsgUnjail,
    MsgVerifyInvariant,
    MsgVote,
    MsgWithdrawDelegatorReward,
    MsgWithdrawValidatorCommission,
)

INF_VERSION = 1 << 30  # "open-ended" ToVersion


@dataclass(frozen=True)
class VersionedModule:
    """One module registration (module.go VersionedModule parity)."""

    name: str
    from_version: int
    to_version: int = INF_VERSION
    msg_types: Tuple[type, ...] = ()
    # target_version -> migration(app); run when upgrading TO that version
    migrations: Tuple[Tuple[int, Callable], ...] = ()

    def active_at(self, version: int) -> bool:
        return self.from_version <= version <= self.to_version


class Manager:
    """The versioned module manager (app/module/module.go Manager)."""

    def __init__(self, modules: Sequence[VersionedModule] = ()):
        self._modules: List[VersionedModule] = []
        # version -> accepted msg-type frozenset; the gatekeeper asks
        # once per tx, so this is recomputed only when the module set
        # changes (frozen: the cached object is handed out directly)
        self._accept_cache: Dict[int, frozenset] = {}
        for m in modules:
            self.register(m)

    def register(self, module: VersionedModule) -> None:
        if module.from_version > module.to_version:
            raise ValueError(
                f"module {module.name}: FromVersion {module.from_version} > "
                f"ToVersion {module.to_version}"
            )
        for existing in self._modules:
            if existing.name == module.name and not (
                module.to_version < existing.from_version
                or module.from_version > existing.to_version
            ):
                raise ValueError(
                    f"module {module.name}: overlapping version ranges"
                )
        self._modules.append(module)
        self._accept_cache.clear()

    def unregister(self, name: str, from_version: Optional[int] = None) -> None:
        self._modules = [
            m
            for m in self._modules
            if not (
                m.name == name
                and (from_version is None or m.from_version == from_version)
            )
        ]
        self._accept_cache.clear()

    def modules_at(self, version: int) -> List[VersionedModule]:
        return [m for m in self._modules if m.active_at(version)]

    def supported_versions(self) -> List[int]:
        """Every version in some module's range, bounded by declared
        endpoints (a version is supported iff at least one module declared
        it explicitly as a From bound or it sits inside all ranges)."""
        bounds: Set[int] = set()
        for m in self._modules:
            bounds.add(m.from_version)
            if m.to_version != INF_VERSION:
                bounds.add(m.to_version)
        return sorted(v for v in bounds if self.modules_at(v))

    def msgs_accepted_at(self, version: int) -> frozenset:
        cached = self._accept_cache.get(version)
        if cached is not None:
            return cached
        active = self.modules_at(version)
        if version not in self.supported_versions():
            raise ValueError(f"unsupported app version {version}")
        out: Set[type] = set()
        for m in active:
            out.update(m.msg_types)
        frozen = frozenset(out)
        self._accept_cache[version] = frozen
        return frozen

    def run_migrations(self, app, from_version: int, to_version: int) -> List[str]:
        """RunMigrations parity (module.go:231): step through every version
        between (from, to], applying each active module's migrations
        registered for that target version, in registration order."""
        log: List[str] = []
        for v in range(from_version + 1, to_version + 1):
            for m in self._modules:
                if not m.active_at(v):
                    continue
                for target, fn in m.migrations:
                    if target == v:
                        fn(app)
                        log.append(f"{m.name}: {fn.__name__} -> v{v}")
        return log


# ---------------------------------------------------------------------------
# the default registry — mirrors app/app.go:435-528 module wiring
# ---------------------------------------------------------------------------


def _migrate_v2_minfee(app) -> None:
    """v1 -> v2: introduce the x/minfee network min gas price param."""
    from celestia_tpu.appconsts import GLOBAL_MIN_GAS_PRICE_PPM

    if not app.params.has("minfee", "NetworkMinGasPricePpm"):
        app.params.set("minfee", "NetworkMinGasPricePpm", GLOBAL_MIN_GAS_PRICE_PPM)


DEFAULT_MODULES: Tuple[VersionedModule, ...] = (
    VersionedModule("bank", V1_VERSION, msg_types=(MsgSend,)),
    VersionedModule("blob", V1_VERSION, msg_types=(MsgPayForBlobs,)),
    VersionedModule(
        "staking", V1_VERSION, msg_types=(MsgDelegate, MsgUndelegate)
    ),
    VersionedModule(
        "blobstream", V1_VERSION, msg_types=(MsgRegisterEVMAddress,)
    ),
    VersionedModule("params", V1_VERSION, msg_types=(MsgParamChange,)),
    VersionedModule(
        "gov", V1_VERSION, msg_types=(MsgSubmitProposal, MsgVote)
    ),
    VersionedModule("mint", V1_VERSION),
    VersionedModule("paramfilter", V1_VERSION),
    VersionedModule("tokenfilter", V1_VERSION),
    VersionedModule(
        "feegrant",
        V1_VERSION,
        msg_types=(MsgGrantAllowance, MsgRevokeAllowance),
    ),
    VersionedModule(
        "authz",
        V1_VERSION,
        msg_types=(MsgAuthzGrant, MsgAuthzRevoke, MsgExec),
    ),
    VersionedModule(
        "distribution",
        V1_VERSION,
        msg_types=(
            MsgWithdrawDelegatorReward,
            MsgWithdrawValidatorCommission,
            MsgFundCommunityPool,
            MsgSetWithdrawAddress,
        ),
    ),
    VersionedModule("slashing", V1_VERSION, msg_types=(MsgUnjail,)),
    VersionedModule("evidence", V1_VERSION, msg_types=(MsgSubmitEvidence,)),
    VersionedModule("crisis", V1_VERSION, msg_types=(MsgVerifyInvariant,)),
    VersionedModule(
        "vesting", V1_VERSION, msg_types=(MsgCreateVestingAccount,)
    ),
    # x/upgrade signalling arrives in v2 (ADR-018); x/minfee's param
    # subspace is created by its v2 migration
    VersionedModule(
        "upgrade",
        V2_VERSION,
        msg_types=(MsgSignalVersion, MsgTryUpgrade),
    ),
    VersionedModule(
        "minfee", V2_VERSION, migrations=((V2_VERSION, _migrate_v2_minfee),)
    ),
)

MANAGER = Manager(DEFAULT_MODULES)


# ---------------------------------------------------------------------------
# module-level convenience API (used by App + ante gatekeeper)
# ---------------------------------------------------------------------------


def msgs_accepted_at(app_version: int) -> Set[type]:
    return MANAGER.msgs_accepted_at(app_version)


def supported_versions() -> List[int]:
    return MANAGER.supported_versions()


def run_migrations(app, from_version: int, to_version: int) -> List[str]:
    return MANAGER.run_migrations(app, from_version, to_version)


def register_version(version: int, msgs: Set[type]) -> None:
    """Register a future app version (what a new binary release does): a
    synthetic module carrying that version's new message set."""
    MANAGER.register(
        VersionedModule(
            f"release-v{version}", version, msg_types=tuple(msgs)
        )
    )


def unregister_version(version: int) -> None:
    MANAGER.unregister(f"release-v{version}")


def register_migration(target_version: int, fn: Callable) -> None:
    """Attach a standalone migration (testing hook)."""
    MANAGER.register(
        VersionedModule(
            f"migration-{fn.__name__}-v{target_version}",
            target_version,
            migrations=((target_version, fn),),
        )
    )
