"""Multi-versioned state machine registry (ADR-022 parity).

The reference registers modules with [FromVersion, ToVersion] ranges and a
versioned configurator records which messages each app version accepts
(app/module/module.go:20-100, configurator.go:34-76); the ante
MsgVersioningGateKeeper consults it.  Here: per-version accepted message
sets + migration callbacks run on upgrade (module.go:231 RunMigrations).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Set, Type

from celestia_tpu.appconsts import V1_VERSION, V2_VERSION
from celestia_tpu.state.tx import (
    MsgDelegate,
    MsgParamChange,
    MsgPayForBlobs,
    MsgRegisterEVMAddress,
    MsgSend,
    MsgSignalVersion,
    MsgTryUpgrade,
    MsgUndelegate,
)

_V1_MSGS: Set[type] = {
    MsgSend,
    MsgPayForBlobs,
    MsgDelegate,
    MsgUndelegate,
    MsgRegisterEVMAddress,
    MsgParamChange,
}

# v2 adds the x/upgrade signalling msgs (and the x/minfee param subspace)
_V2_MSGS: Set[type] = _V1_MSGS | {MsgSignalVersion, MsgTryUpgrade}

_ACCEPTED: Dict[int, Set[type]] = {
    V1_VERSION: _V1_MSGS,
    V2_VERSION: _V2_MSGS,
}


def msgs_accepted_at(app_version: int) -> Set[type]:
    try:
        return _ACCEPTED[app_version]
    except KeyError:
        raise ValueError(f"unsupported app version {app_version}") from None


def supported_versions() -> List[int]:
    return sorted(_ACCEPTED)


def register_version(version: int, msgs: Set[type]) -> None:
    """Register a new app version's accepted-message set (what a future
    binary release does; module.go version-range registration parity)."""
    _ACCEPTED[version] = set(msgs)


# --- migrations -------------------------------------------------------------

# target_version -> list of callables(app) run when upgrading TO that version
_MIGRATIONS: Dict[int, List[Callable]] = {}


def register_migration(target_version: int, fn: Callable) -> None:
    _MIGRATIONS.setdefault(target_version, []).append(fn)


def run_migrations(app, from_version: int, to_version: int) -> List[str]:
    """RunMigrations parity: apply every registered migration between
    versions in order; returns a log."""
    log = []
    for v in range(from_version + 1, to_version + 1):
        for fn in _MIGRATIONS.get(v, []):
            fn(app)
            log.append(f"migration {fn.__name__} -> v{v}")
    return log


def _migrate_v2_minfee(app) -> None:
    """v1 -> v2: introduce the x/minfee network min gas price param."""
    from celestia_tpu.appconsts import GLOBAL_MIN_GAS_PRICE_PPM

    if not app.params.has("minfee", "NetworkMinGasPricePpm"):
        app.params.set("minfee", "NetworkMinGasPricePpm", GLOBAL_MIN_GAS_PRICE_PPM)


register_migration(V2_VERSION, _migrate_v2_minfee)
