"""Compact sparse Merkle tree with content-addressed nodes.

Fills the role IAVL plays in the reference (the commit-multistore mounted
at /root/reference/app/app.go:242): an incrementally-maintained
authenticated map per module store, so a commit costs O(writes * log N)
instead of rehashing all state, and any (key, value) can be proven against
the store root — which in turn folds into the block's app hash.

Design (tpu-repo-native, not an IAVL port):
- keys are placed at the path given by the bits of sha256(key); a subtree
  holding exactly one key is collapsed to a single leaf node (so depth is
  ~log2(N) expected, not 256);
- nodes are CONTENT-ADDRESSED: node_hash -> encoding in a plain dict.
  Updates insert new nodes and never mutate old ones, so every historical
  root stays readable for pinned-height proofs at zero copying cost, and
  pruning is a reachability sweep from the roots still retained;
- proofs are the sibling hashes along the search path.  Non-membership is
  proven by an empty slot or by a colliding-prefix leaf with a different
  key hash.

Everything here is a pure function over (nodes, root); the client-side
verifiers at the bottom need no node store at all.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Set, Tuple

EMPTY_ROOT = b"\x00" * 32

_LEAF_TAG = b"\x00"
_INNER_TAG = b"\x01"


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def key_hash(key: bytes) -> bytes:
    return _sha(key)


def value_hash(value: bytes) -> bytes:
    return _sha(value)


def leaf_hash(kh: bytes, vh: bytes) -> bytes:
    return _sha(_LEAF_TAG + kh + vh)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha(_INNER_TAG + left + right)


def _bit(kh: bytes, depth: int) -> int:
    return (kh[depth >> 3] >> (7 - (depth & 7))) & 1


def _put_leaf(nodes: Dict[bytes, bytes], kh: bytes, vh: bytes) -> bytes:
    h = leaf_hash(kh, vh)
    nodes[h] = _LEAF_TAG + kh + vh
    return h


def _put_inner(nodes: Dict[bytes, bytes], left: bytes, right: bytes) -> bytes:
    h = inner_hash(left, right)
    nodes[h] = _INNER_TAG + left + right
    return h


def _node(nodes: Dict[bytes, bytes], h: bytes) -> bytes:
    if h == EMPTY_ROOT:
        raise KeyError("empty subtree has no node")
    enc = nodes.get(h)
    if enc is None:
        raise KeyError(f"missing merkle node {h.hex()} (pruned?)")
    return enc


def _walk(
    nodes: Dict[bytes, bytes], root: bytes, kh: bytes
) -> Tuple[List[Tuple[bytes, int]], bytes]:
    """Descend from root along kh's bits through inner nodes.

    Returns (stack, terminal) where stack is [(sibling_hash, my_bit), ...]
    in root->down order and terminal is EMPTY_ROOT or a leaf hash.
    """
    stack: List[Tuple[bytes, int]] = []
    cur = root
    depth = 0
    while cur != EMPTY_ROOT:
        enc = _node(nodes, cur)
        if enc[0:1] == _LEAF_TAG:
            break
        left, right = enc[1:33], enc[33:65]
        b = _bit(kh, depth)
        if b == 0:
            stack.append((right, 0))
            cur = left
        else:
            stack.append((left, 1))
            cur = right
        depth += 1
    return stack, cur


def _rebuild(
    nodes: Dict[bytes, bytes], stack: List[Tuple[bytes, int]], h: bytes
) -> bytes:
    """Fold the replacement subtree hash back up through the stack,
    collapsing inner nodes whose only content is a single leaf."""
    for sibling, bit in reversed(stack):
        if sibling == EMPTY_ROOT and (
            h == EMPTY_ROOT or _node(nodes, h)[0:1] == _LEAF_TAG
        ):
            # an inner node over (leaf, empty) collapses to the leaf;
            # over (empty, empty) it collapses to empty
            continue
        if h == EMPTY_ROOT and _node(nodes, sibling)[0:1] == _LEAF_TAG:
            # the sibling leaf floats up regardless of which side it was on
            h = sibling
            continue
        h = _put_inner(nodes, h, sibling) if bit == 0 else _put_inner(
            nodes, sibling, h
        )
    return h


def smt_update(
    nodes: Dict[bytes, bytes], root: bytes, kh: bytes, vh: bytes
) -> bytes:
    """Set kh -> vh; returns the new root.  O(depth)."""
    stack, terminal = _walk(nodes, root, kh)
    if terminal == EMPTY_ROOT:
        return _rebuild(nodes, stack, _put_leaf(nodes, kh, vh))
    enc = _node(nodes, terminal)
    other_kh = enc[1:33]
    if other_kh == kh:
        return _rebuild(nodes, stack, _put_leaf(nodes, kh, vh))
    # two distinct keys share a prefix: extend the path to their first
    # diverging bit, hanging empties in between
    depth = len(stack)
    d = depth
    while _bit(kh, d) == _bit(other_kh, d):
        d += 1
    new_leaf = _put_leaf(nodes, kh, vh)
    if _bit(kh, d) == 0:
        h = _put_inner(nodes, new_leaf, terminal)
    else:
        h = _put_inner(nodes, terminal, new_leaf)
    for dd in range(d - 1, depth - 1, -1):
        if _bit(kh, dd) == 0:
            h = _put_inner(nodes, h, EMPTY_ROOT)
        else:
            h = _put_inner(nodes, EMPTY_ROOT, h)
    return _rebuild(nodes, stack, h)


def smt_delete(nodes: Dict[bytes, bytes], root: bytes, kh: bytes) -> bytes:
    """Remove kh if present; returns the new root."""
    stack, terminal = _walk(nodes, root, kh)
    if terminal == EMPTY_ROOT:
        return root
    if _node(nodes, terminal)[1:33] != kh:
        return root  # a different key occupies the slot; nothing to delete
    return _rebuild(nodes, stack, EMPTY_ROOT)


def smt_get(
    nodes: Dict[bytes, bytes], root: bytes, kh: bytes
) -> Optional[bytes]:
    _, terminal = _walk(nodes, root, kh)
    if terminal == EMPTY_ROOT:
        return None
    enc = _node(nodes, terminal)
    if enc[1:33] != kh:
        return None
    return enc[33:65]


def smt_build(
    nodes: Dict[bytes, bytes], items: Iterable[Tuple[bytes, bytes]]
) -> bytes:
    """Build a tree from (key_hash, value_hash) pairs; returns the root."""
    root = EMPTY_ROOT
    for kh, vh in items:
        root = smt_update(nodes, root, kh, vh)
    return root


def smt_prove(
    nodes: Dict[bytes, bytes], root: bytes, kh: bytes
) -> Tuple[List[bytes], Optional[Tuple[bytes, bytes]]]:
    """Proof for kh under root: (siblings root->down, terminal leaf).

    leaf is None when the search path ends in an empty slot (pure
    non-membership), else the (key_hash, value_hash) of the leaf found
    there — which proves membership if its key_hash == kh and
    non-membership otherwise.
    """
    stack, terminal = _walk(nodes, root, kh)
    siblings = [s for s, _ in stack]
    if terminal == EMPTY_ROOT:
        return siblings, None
    enc = _node(nodes, terminal)
    return siblings, (enc[1:33], enc[33:65])


def smt_reachable(nodes: Dict[bytes, bytes], roots: Iterable[bytes]) -> Set[bytes]:
    """All node hashes reachable from the given roots (for pruning)."""
    seen: Set[bytes] = set()
    frontier = [r for r in roots if r != EMPTY_ROOT]
    while frontier:
        h = frontier.pop()
        if h in seen:
            continue
        seen.add(h)
        enc = nodes.get(h)
        if enc is None or enc[0:1] == _LEAF_TAG:
            continue
        for child in (enc[1:33], enc[33:65]):
            if child != EMPTY_ROOT and child not in seen:
                frontier.append(child)
    return seen


# ---------------------------------------------------------------------------
# client-side verification (no node store needed)
# ---------------------------------------------------------------------------


def fold_path(
    kh: bytes, siblings: List[bytes], terminal: bytes
) -> bytes:
    """Recompute the root from a terminal subtree hash and the sibling
    path.  The terminal sits at depth len(siblings); position bits come
    from kh (identical to the found leaf's bits over the shared prefix)."""
    h = terminal
    for depth in range(len(siblings) - 1, -1, -1):
        sib = siblings[depth]
        if _bit(kh, depth) == 0:
            h = inner_hash(h, sib)
        else:
            h = inner_hash(sib, h)
    return h


def verify_membership(
    root: bytes,
    key: bytes,
    value: bytes,
    siblings: List[bytes],
    leaf: Optional[Tuple[bytes, bytes]],
) -> bool:
    """True iff (key, value) is committed under root."""
    if leaf is None:
        return False
    kh = key_hash(key)
    lk, lv = leaf
    if lk != kh or lv != value_hash(value):
        return False
    return fold_path(kh, siblings, leaf_hash(lk, lv)) == root


def verify_non_membership(
    root: bytes,
    key: bytes,
    siblings: List[bytes],
    leaf: Optional[Tuple[bytes, bytes]],
) -> bool:
    """True iff key is absent under root."""
    kh = key_hash(key)
    if leaf is None:
        return fold_path(kh, siblings, EMPTY_ROOT) == root
    lk, lv = leaf
    if lk == kh:
        return False
    # the occupying leaf must actually lie on kh's search path
    for depth in range(len(siblings)):
        if _bit(lk, depth) != _bit(kh, depth):
            return False
    return fold_path(kh, siblings, leaf_hash(lk, lv)) == root


def store_roots_hash(roots: Dict[str, bytes]) -> bytes:
    """App hash = hash of the sorted (store name, store root) pairs —
    the root-of-store-roots the reference's commit multistore produces."""
    h = hashlib.sha256()
    for name in sorted(roots):
        h.update(_sha(name.encode()))
        h.update(roots[name])
    return h.digest()


def verify_query_proof(proof: dict, trusted_app_hash: bytes) -> bool:
    """Client-side verification of a MultiStore.prove() result against a
    trusted app hash (the block header's).  Checks, in order: the store
    roots fold to the app hash; the claimed store root is among them; and
    the (key, value) is proven present — or, for value None, absent —
    under that store root."""
    store_roots = {
        n: bytes.fromhex(r) for n, r in proof["store_roots"].items()
    }
    if store_roots_hash(store_roots) != trusted_app_hash:
        return False
    root = store_roots.get(proof["store"])
    if root is None:
        return False
    key = bytes.fromhex(proof["key"])
    siblings = [bytes.fromhex(s) for s in proof["siblings"]]
    leaf = (
        (bytes.fromhex(proof["leaf"][0]), bytes.fromhex(proof["leaf"][1]))
        if proof.get("leaf")
        else None
    )
    if proof["value"] is None:
        return verify_non_membership(root, key, siblings, leaf)
    return verify_membership(
        root, key, bytes.fromhex(proof["value"]), siblings, leaf
    )
