"""celestia-tpu — the node daemon + client command tree.

Parity with the reference CLI (cmd/celestia-appd/cmd/root.go:55-161):
``init``, ``start``, ``keys``, ``tx`` (bank send / blob pay-for-blob),
``query`` (balance / tx / block / status / proof), ``status``, plus the
``blocktime`` tool (tools/blocktime/main.go:20-96).

Run as ``python -m celestia_tpu.cli <command>`` or via the celestia-tpu
entry point.  The ``start`` command serves the gRPC node service
(node/server.py) that every client command talks to over the network.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from pathlib import Path

DEFAULT_HOME = os.path.expanduser("~/.celestia-tpu")


def _home(args) -> str:
    return args.home or os.environ.get("CELESTIA_HOME", DEFAULT_HOME)


# ---------------------------------------------------------------------------
# keyring (file-backed, seed keys)
# ---------------------------------------------------------------------------


def _keyring_dir(home: str) -> Path:
    d = Path(home) / "keyring"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _load_key(home: str, name: str):
    from celestia_tpu.utils.secp256k1 import PrivateKey

    path = _keyring_dir(home) / f"{name}.json"
    if not path.exists():
        raise SystemExit(f"key {name!r} not found in {path.parent}")
    info = json.loads(path.read_text())
    return PrivateKey(int(info["priv"], 16))


def cmd_keys(args) -> int:
    from celestia_tpu.utils.secp256k1 import PrivateKey

    home = _home(args)
    kd = _keyring_dir(home)
    if args.keys_cmd == "add":
        path = kd / f"{args.name}.json"
        if path.exists():
            raise SystemExit(f"key {args.name!r} already exists")
        key = PrivateKey.from_seed(os.urandom(32))
        addr = key.public_key().address()
        path.write_text(
            json.dumps({"priv": f"{key.d:064x}", "address": addr.hex()})
        )
        print(json.dumps({"name": args.name, "address": addr.hex()}))
    elif args.keys_cmd == "list":
        for p in sorted(kd.glob("*.json")):
            info = json.loads(p.read_text())
            print(json.dumps({"name": p.stem, "address": info["address"]}))
    elif args.keys_cmd == "show":
        key = _load_key(home, args.name)
        print(
            json.dumps(
                {
                    "name": args.name,
                    "address": key.public_key().address().hex(),
                    "pubkey": key.public_key().compressed().hex(),
                }
            )
        )
    return 0


# ---------------------------------------------------------------------------
# init / start
# ---------------------------------------------------------------------------


def cmd_init(args) -> int:
    from celestia_tpu.node.config import init_home

    home = _home(args)
    if args.genesis and args.fund_keyring:
        raise SystemExit(
            "--fund-keyring conflicts with --genesis: a shared genesis "
            "replaces the generated one; add the accounts to the shared "
            "genesis file instead"
        )
    extra = []
    if args.fund_keyring:
        for p in sorted(_keyring_dir(home).glob("*.json")):
            info = json.loads(p.read_text())
            extra.append((bytes.fromhex(info["address"]), args.fund_keyring))
    root = init_home(
        home, chain_id=args.chain_id, overwrite=args.overwrite,
        extra_accounts=extra,
    )
    chain_id = args.chain_id
    if args.genesis:
        shared = json.loads(Path(args.genesis).read_text())
        chain_id = shared.get("chain_id", chain_id)
        (root / "config" / "genesis.json").write_text(
            json.dumps(shared, indent=1)
        )
    print(
        json.dumps(
            {
                "home": str(root),
                "chain_id": chain_id,
                "funded_accounts": len(extra),
            }
        )
    )
    return 0


def cmd_start(args) -> int:
    # test/CI hook: force the jax platform before first device use (the
    # JAX_PLATFORMS env var alone is overridden by sitecustomize on some
    # hosts) — lets multi-process harnesses run nodes on the CPU backend
    platform = os.environ.get("CELESTIA_JAX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)

    from celestia_tpu.node.config import load_config
    from celestia_tpu.node.server import NodeServer
    from celestia_tpu.node.testnode import TestNode
    from celestia_tpu.utils.logging import Logger
    from celestia_tpu.utils.secp256k1 import PrivateKey

    home = _home(args)
    overrides = {}
    if args.grpc_address:
        overrides["grpc.address"] = args.grpc_address
    if args.block_interval is not None:
        overrides["consensus.block_interval_s"] = args.block_interval
    if args.v2_upgrade_height is not None:
        overrides["v2_upgrade_height"] = args.v2_upgrade_height
    cfg = load_config(home, overrides=overrides)
    log = Logger(level=cfg.log.level, fmt=cfg.log.format, to_file=cfg.log.to_file)

    trace_blocks = getattr(args, "trace_blocks", None)
    if getattr(args, "trace", False) or trace_blocks is not None:
        # block-lifecycle span tracing (utils/tracing.py): ring-buffered
        # last-N-blocks, served over the TraceDump RPC; near-zero
        # overhead would still argue for off-by-default — this is the
        # operator's explicit opt-in (CELESTIA_TPU_TRACE works too).
        # --trace-blocks alone implies --trace: sizing a ring you did
        # not turn on would otherwise be a silent no-op.
        from celestia_tpu.utils import tracing

        tracing.enable(trace_blocks)
        log.info("block tracing enabled", blocks=tracing.TRACER.max_blocks)

    if getattr(args, "mesh", None) is not None:
        # multi-chip mesh override (parallel/mesh.py): validated HERE so
        # a malformed spec fails the start loudly instead of poisoning
        # the mesh at the first block
        from celestia_tpu.parallel import mesh as mesh_mod

        try:
            mesh_mod.configure(args.mesh)
        except ValueError as e:
            raise SystemExit(f"--mesh: {e}")

    genesis_path = Path(home) / "config" / "genesis.json"
    if not genesis_path.exists():
        raise SystemExit(f"no genesis at {genesis_path}; run `init` first")
    genesis = json.loads(genesis_path.read_text())
    key_path = Path(home) / "config" / "priv_validator_key.json"
    validator_key = None
    if key_path.exists():
        validator_key = PrivateKey(
            int(json.loads(key_path.read_text())["priv_key"], 16)
        )

    data_dir = str(Path(home) / "data")
    snapshot_dir = str(Path(home) / "data" / "snapshots")
    from celestia_tpu.node.snapshots import SnapshotStore

    latest_snap = SnapshotStore(snapshot_dir).latest()
    blocks_log = Path(data_dir) / "blocks.log"
    node = None
    if blocks_log.exists() and blocks_log.stat().st_size > 0:
        # primary restart path: the append-only disk logs carry the whole
        # chain to the last fsynced block (app.go:657-661 LoadLatestVersion
        # role); snapshots below remain as the state-sync fallback
        node = TestNode(
            chain_id=genesis.get("chain_id", cfg.chain_id),
            genesis=genesis,
            validator_key=validator_key,
            block_interval_ns=int(cfg.consensus.block_interval_s * 1e9),
            auto_produce=False,
            min_gas_price=cfg.min_gas_price,
            v2_upgrade_height=cfg.v2_upgrade_height,
            snapshot_dir=snapshot_dir,
            snapshot_interval=cfg.snapshot.interval,
            snapshot_keep_recent=cfg.snapshot.keep_recent,
            data_dir=data_dir,
        )
        if node.blocks:
            log.info(
                "recovered chain from disk",
                height=node.height,
                app_hash=node.app.store.committed_hash(node.height).hex()[:16],
            )
        elif latest_snap is not None:
            # the block log was fully torn; the snapshot is newer than a
            # genesis reset, so prefer it.  The throwaway node has already
            # wiped + reopened the logs and seeded genesis STATE records —
            # release its file handles and clear those records so the
            # snapshot node reopens a clean data dir (no stale
            # pre-checkpoint genesis state, no leaked fds)
            node.close()
            for name in ("state.log", "blocks.log"):
                p = Path(data_dir) / name
                if p.exists():
                    p.unlink()
            node = None
        else:
            log.info("block log unreadable; restarted from genesis")
    if node is not None:
        pass
    elif latest_snap is not None:
        # restart path: resume from the latest state-sync snapshot instead
        # of silently resetting to genesis (root.go:227-243 restore wiring)
        node = TestNode.from_snapshot(
            snapshot_dir,
            block_interval_ns=int(cfg.consensus.block_interval_s * 1e9),
            auto_produce=False,
            snapshot_interval=cfg.snapshot.interval,
            snapshot_keep_recent=cfg.snapshot.keep_recent,
            validator_key=validator_key,
            min_gas_price=cfg.min_gas_price,
            v2_upgrade_height=cfg.v2_upgrade_height,
            data_dir=data_dir,
        )
        log.info(
            "restored from snapshot",
            height=latest_snap.height,
            app_hash=latest_snap.app_hash.hex()[:16],
        )
    else:
        node = TestNode(
            chain_id=genesis.get("chain_id", cfg.chain_id),
            genesis=genesis,
            validator_key=validator_key,
            block_interval_ns=int(cfg.consensus.block_interval_s * 1e9),
            auto_produce=False,
            min_gas_price=cfg.min_gas_price,
            v2_upgrade_height=cfg.v2_upgrade_height,
            snapshot_dir=snapshot_dir,
            snapshot_interval=cfg.snapshot.interval,
            snapshot_keep_recent=cfg.snapshot.keep_recent,
            data_dir=data_dir,
        )
    if node.genesis_doc is None:
        # recovery / snapshot-restore paths skip InitChain, but the home
        # still has the genesis file — keep serving it to joining peers
        node.genesis_doc = genesis
    if getattr(args, "bft_valset", None):
        # two-phase BFT mode: this node votes with its own key and
        # commits only on a 2/3 precommit quorum it verified itself
        valset = json.loads(Path(args.bft_valset).read_text())
        node.enable_bft(valset)
        log.info("BFT consensus enabled", validators=len(valset))
    # Pre-warm the device extension programs BEFORE serving: the block
    # producer holds the service lock across the first extension of each
    # square size, and a cold TPU compile there (~20-40 s) would stall
    # every RPC past its deadline.  Sizes are configurable; warming at
    # boot trades startup seconds for never stalling a live block.
    raw_sizes = str(getattr(args, "warm_squares", "1,2,4"))
    try:
        warm_sizes = [int(s) for s in raw_sizes.split(",") if s.strip()]
    except ValueError:
        raise SystemExit(f"--warm-squares must be comma-separated ints: {raw_sizes!r}")
    for s in warm_sizes:
        if not 1 <= s <= 128 or s & (s - 1):
            raise SystemExit(
                f"--warm-squares sizes must be powers of two in [1, 128], got {s}"
            )
    if warm_sizes:
        from celestia_tpu.utils.device import backend_available

        if not backend_available(timeout_s=120.0, accept_cpu=True):
            # a dead tunnel HANGS backend init — probed in a child so the
            # node still starts and serves; first extensions will compile
            # lazily if/when the backend returns
            log.warn("device backend unreachable; skipping program warm-up")
            warm_sizes = []
    if warm_sizes:
        import numpy as _np

        from celestia_tpu.da import dah as _dah

        t_warm = time.time()
        for s in warm_sizes:
            _dah.extend_and_header(
                _np.zeros((s, s, 512), dtype=_np.uint8)
            )
        log.info(
            "device programs warmed",
            sizes=",".join(map(str, warm_sizes)),
            seconds=round(time.time() - t_warm, 1),
        )
        # the warm-up already initialized the backend, so resolving the
        # mesh here is free — the operator sees at boot whether live
        # extends will shard (lazy resolution at the first block is the
        # fallback when warm-up was skipped)
        from celestia_tpu.parallel import mesh as mesh_mod

        if mesh_mod.device_mesh() is not None:
            shape = mesh_mod.mesh_shape()
            log.info(
                "multi-chip mesh active",
                data=shape[0], row=shape[1],
            )
            # warm the SHARDED programs too: the live path routes
            # through them on a mesh-active node, so without this the
            # first real block would pay the structure-bound shard_map
            # compile in the hot path — exactly the stall --warm-squares
            # exists to prevent
            from celestia_tpu.parallel import sharded as _sharded

            t_warm = time.time()
            warmed_sharded = []
            # mesh-eligible subset of the warm sizes; when NONE is
            # eligible (default '1,2,4' vs a wide row axis — every size
            # falls back) warm the smallest eligible size instead, so at
            # least one sharded program + the collective machinery
            # compiles at boot rather than inside the first big block
            # (operators size --warm-squares up for full coverage)
            shard_sizes = [
                s for s in warm_sizes
                if mesh_mod.mesh_for_square(s, count_fallback=False)
                is not None
            ]
            if not shard_sizes:
                row = shape[1]
                if row <= 128:
                    shard_sizes = [row]
            try:
                for s in shard_sizes:
                    m = mesh_mod.mesh_for_square(s, count_fallback=False)
                    if m is None:
                        continue
                    _sharded.extend_and_roots_sharded(
                        _np.zeros((s, s, 512), dtype=_np.uint8), m,
                        record_stats=False,
                    )
                    warmed_sharded.append(s)
            except Exception as e:
                # the same failure one block later would merely poison
                # the mesh and serve single-device — boot must degrade
                # identically, never exit
                mesh_mod.poison(f"sharded warm-up failed: {e!r}")
                log.warn(
                    "multi-chip mesh disabled",
                    reason=mesh_mod.poisoned(),
                )
            if warmed_sharded:
                log.info(
                    "sharded device programs warmed",
                    sizes=",".join(map(str, warmed_sharded)),
                    seconds=round(time.time() - t_warm, 1),
                )
        elif mesh_mod.poisoned():
            log.warn("multi-chip mesh disabled", reason=mesh_mod.poisoned())
    device_profile_dir = None
    # CELESTIA_TPU_DEVICE_PROFILE is the env equivalent of the flag
    # (same contract as CELESTIA_TPU_TRACE): the flag wins when both
    # are present; truthy values mean "capture into the default dir",
    # explicit falsy values ("0"/"false"/"no"/"off") mean OFF — an
    # operator overriding an orchestration template must not end up
    # capturing into a directory literally named ./0 — anything else
    # is the capture directory itself
    env_profile = os.environ.get("CELESTIA_TPU_DEVICE_PROFILE", "").strip()
    flag_profile = getattr(args, "device_profile", None)
    if flag_profile is None and env_profile:
        low = env_profile.lower()
        if low in ("1", "true", "yes", "on"):
            flag_profile = ""
        elif low not in ("0", "false", "no", "off"):
            flag_profile = env_profile
    if flag_profile is not None:
        # optional XLA profiler capture (utils/devprof.py): TensorBoard/
        # XPlane per-op device timelines next to the Chrome device track.
        # Degrades to a logged note on platforms that cannot capture —
        # the flag without a TPU must never kill the node.
        from celestia_tpu.utils import devprof

        device_profile_dir = flag_profile or str(
            Path(home) / "data" / "device-profile"
        )
        if devprof.start_profiler(device_profile_dir):
            log.info("device profiler capturing", dir=device_profile_dir)
        else:
            log.warn(
                "device profiler unavailable on this platform; "
                "continuing without capture"
            )
            device_profile_dir = None
    # --host-profile without a value (the -1 sentinel) means "the
    # default rate"; an EXPLICIT 0 means off — matching the sibling
    # --timeseries-interval convention, so a wrapper templating the
    # flag can disable profiling without dropping the flag entirely
    raw_hp = getattr(args, "host_profile", None)
    host_profile_hz = None
    if raw_hp is not None and raw_hp != 0:
        from celestia_tpu.utils import hostprof

        host_profile_hz = raw_hp if raw_hp > 0 else hostprof.DEFAULT_HZ
    flight_dir = getattr(args, "flight_dir", None)
    server = NodeServer(
        node,
        address=cfg.grpc.address,
        # validator mode: an external driver paces consensus; no self-loop
        block_interval_s=(
            None
            if args.validator or getattr(args, "bft_valset", None)
            else cfg.consensus.block_interval_s
        ),
        # plain-HTTP /metrics for a stock Prometheus (off by default)
        metrics_port=getattr(args, "metrics_port", None),
        # continuous telemetry snapshots (0 disables the sampler)
        timeseries_interval_s=getattr(args, "timeseries_interval", 5.0),
        # continuous host profiling (utils/hostprof.py; off by default)
        host_profile_hz=host_profile_hz,
        # anomaly flight recorder (utils/flight.py; off by default)
        flight_dir=flight_dir,
    )
    server.start()
    if server.metrics_http is not None:
        log.info(
            "metrics HTTP endpoint", address=server.metrics_http.address
        )
    if host_profile_hz:
        from celestia_tpu.utils import hostprof

        log.info("host profiler sampling", hz=hostprof.hz())
    if flight_dir:
        log.info("flight recorder armed", dir=flight_dir)
    gossip = None
    if getattr(args, "peers", None) and getattr(args, "bft_valset", None):
        # p2p mesh mode: flood consensus messages directly between
        # validators, run own round timers, gossip txs want/have — the
        # bft-relay becomes an optional observer (node/gossip.py)
        from celestia_tpu.node.gossip import GossipEngine

        gossip = GossipEngine(
            node,
            [a for a in args.peers.split(",") if a],
            block_gap_s=cfg.consensus.block_interval_s,
            logger=log.with_fields(mod="gossip"),
        )
        gossip.start()
        log.info("gossip mesh enabled", peers=len(gossip.peer_addrs))
    log.info(
        "node started",
        chain_id=node.chain_id,
        grpc=server.address,
        block_interval_s=cfg.consensus.block_interval_s,
    )
    print(
        json.dumps(
            {
                "grpc": server.address,
                "chain_id": node.chain_id,
                **(
                    {"metrics_http": server.metrics_http.address}
                    if server.metrics_http is not None
                    else {}
                ),
            }
        ),
        flush=True,
    )
    try:
        while True:
            # celint: allow(sanctioned-retry) — the serve command's idle park; all work happens on server/gossip threads
            time.sleep(3600)
    except KeyboardInterrupt:
        log.info("shutting down")
        if gossip is not None:
            gossip.stop()
        server.stop()
        if device_profile_dir is not None:
            from celestia_tpu.utils import devprof

            stopped = devprof.stop_profiler()
            if stopped:
                log.info("device profiler capture written", dir=stopped)
    return 0


# ---------------------------------------------------------------------------
# tx / query (remote client commands)
# ---------------------------------------------------------------------------


def _remote(args):
    from celestia_tpu.client.remote import RemoteNode

    return RemoteNode(args.node, timeout_s=getattr(args, "timeout", 120.0))


def cmd_tx(args) -> int:
    from celestia_tpu.client.signer import Signer

    home = _home(args)
    node = _remote(args)
    key = _load_key(home, getattr(args, "from_key"))
    signer = Signer(node, key)
    if args.tx_cmd == "send":
        from celestia_tpu.state.tx import MsgSend

        msg = MsgSend(
            from_addr=signer.address,
            to_addr=bytes.fromhex(args.to),
            amount=int(args.amount),
        )
        res = signer.submit_tx([msg])
    elif args.tx_cmd == "pay-for-blob":
        from celestia_tpu.da.blob import Blob
        from celestia_tpu.da.namespace import Namespace

        if args.data.startswith("@"):
            data = Path(args.data[1:]).read_bytes()
        else:
            data = bytes.fromhex(args.data)
        ns = Namespace.v0(bytes.fromhex(args.namespace))
        res = signer.submit_pay_for_blob([Blob(ns, data)])
    elif args.tx_cmd == "delegate":
        from celestia_tpu.state.tx import MsgDelegate

        res = signer.submit_tx([
            MsgDelegate(
                signer.address, bytes.fromhex(args.validator),
                int(args.amount),
            )
        ])
    elif args.tx_cmd == "undelegate":
        from celestia_tpu.state.tx import MsgUndelegate

        res = signer.submit_tx([
            MsgUndelegate(
                signer.address, bytes.fromhex(args.validator),
                int(args.amount),
            )
        ])
    elif args.tx_cmd == "withdraw-rewards":
        from celestia_tpu.state.tx import MsgWithdrawDelegatorReward

        res = signer.submit_tx([
            MsgWithdrawDelegatorReward(
                signer.address, bytes.fromhex(args.validator)
            )
        ])
    elif args.tx_cmd == "withdraw-commission":
        from celestia_tpu.state.tx import MsgWithdrawValidatorCommission

        res = signer.submit_tx([MsgWithdrawValidatorCommission(signer.address)])
    elif args.tx_cmd == "fund-community-pool":
        from celestia_tpu.state.tx import MsgFundCommunityPool

        res = signer.submit_tx([
            MsgFundCommunityPool(signer.address, int(args.amount))
        ])
    elif args.tx_cmd == "grant-allowance":
        from celestia_tpu.state.modules.feegrant import KIND_BASIC, KIND_PERIODIC
        from celestia_tpu.state.tx import MsgGrantAllowance

        res = signer.submit_tx([
            MsgGrantAllowance(
                signer.address, bytes.fromhex(args.grantee),
                KIND_PERIODIC if args.period_ns else KIND_BASIC,
                int(args.spend_limit), int(args.expiration_ns),
                int(args.period_ns), int(args.period_spend_limit),
            )
        ])
    elif args.tx_cmd == "revoke-allowance":
        from celestia_tpu.state.tx import MsgRevokeAllowance

        res = signer.submit_tx([
            MsgRevokeAllowance(signer.address, bytes.fromhex(args.grantee))
        ])
    elif args.tx_cmd == "authz-grant":
        from celestia_tpu.state.tx import MsgAuthzGrant

        res = signer.submit_tx([
            MsgAuthzGrant(
                signer.address, bytes.fromhex(args.grantee),
                int(args.msg_type), int(args.spend_limit),
                int(args.expiration_ns),
            )
        ])
    elif args.tx_cmd == "unjail":
        from celestia_tpu.state.tx import MsgUnjail

        res = signer.submit_tx([MsgUnjail(signer.address)])
    else:  # pragma: no cover
        raise SystemExit(f"unknown tx command {args.tx_cmd}")
    # submit_tx / submit_pay_for_blob broadcast AND poll-confirm; the
    # result carries the inclusion height
    out = {
        "code": res.code,
        "txhash": res.tx_hash.hex(),
        "log": res.log,
        "height": res.height,
    }
    print(json.dumps(out))
    return 0 if res.code == 0 else 1


def cmd_query(args) -> int:
    node = _remote(args)
    if args.query_cmd == "balance":
        value = node.abci_query("store/bank/balance", {"address": args.address})
        print(json.dumps({"address": args.address, "balance": value}))
    elif args.query_cmd == "account":
        value = node.abci_query("custom/auth/account", {"address": args.address})
        print(json.dumps(value))
    elif args.query_cmd == "tx":
        info = node.get_tx(bytes.fromhex(args.hash))
        print(json.dumps(info if info else {"found": False}))
    elif args.query_cmd == "txs":
        value = node.abci_query("custom/tx/search", {"event": args.event})
        print(json.dumps(value))
    elif args.query_cmd == "state-proof":
        # fetch + VERIFY a (store, key) membership proof against the
        # block header's app hash, like a light client would
        from celestia_tpu.state.merkle import verify_query_proof

        data = {"store": args.store, "key": args.key}
        if args.height:
            data["height"] = args.height
        proof = node.abci_query("store/proof", data)
        trusted = bytes.fromhex(node.block(proof["height"])["app_hash"])
        ok = verify_query_proof(proof, trusted)
        print(json.dumps({"verified": ok, **proof}))
        if not ok:
            return 1
    elif args.query_cmd == "block":
        print(json.dumps(node.block(int(args.height))))
    elif args.query_cmd == "param":
        value = node.abci_query(
            "custom/params/param", {"subspace": args.subspace, "key": args.key}
        )
        print(json.dumps({"value": value}))
    elif args.query_cmd == "share-proof":
        value = node.abci_query(
            "custom/proof/share",
            {"height": args.height, "start": args.start, "end": args.end},
        )
        print(json.dumps(value))
    elif args.query_cmd == "tx-proof":
        value = node.abci_query(
            "custom/proof/tx", {"height": args.height, "tx_index": args.index}
        )
        print(json.dumps(value))
    elif args.query_cmd == "rewards":
        value = node.abci_query(
            "custom/distribution/rewards",
            {"delegator": args.delegator, "validator": args.validator},
        )
        print(json.dumps(value))
    elif args.query_cmd == "community-pool":
        print(json.dumps(node.abci_query(
            "custom/distribution/community-pool", {}
        )))
    elif args.query_cmd == "signing-info":
        print(json.dumps(node.abci_query(
            "custom/slashing/signing-info", {"validator": args.validator}
        )))
    elif args.query_cmd == "invariants":
        print(json.dumps(node.abci_query("custom/crisis/invariants", {})))
    elif args.query_cmd == "metrics":
        # raw Prometheus text — pipe it to a file or a scraper probe
        sys.stdout.write(node.metrics())
    elif args.query_cmd == "timeseries":
        # the continuous-telemetry ring: snapshots + per-metric rates
        # (the server records one fresh sample per call, so repeated
        # queries always have a computable derivative)
        out = node.time_series(last=args.last or None)
        print(json.dumps({
            "node_id": out.get("node_id", ""),
            "samples_kept": out.get("samples_kept", 0),
            "max_samples": out.get("max_samples", 0),
            "snapshots": out.get("snapshots", []),
            "rates": out.get("rates", {}),
        }, indent=1 if args.pretty else None))
    elif args.query_cmd == "alerts":
        # the declarative alert engine's verdicts over the same ring
        out = node.time_series(last=1)
        alerts = out.get("alerts", [])
        firing = [a for a in alerts if a.get("firing")]
        print(json.dumps({
            "node_id": out.get("node_id", ""),
            "firing": len(firing),
            "alerts": firing if args.firing_only else alerts,
        }, indent=1))
        if firing and args.fail_on_firing:
            return 1
    elif args.query_cmd == "block-scorecard":
        # the per-height block scorecard ring: prepare/process walls,
        # extend leg + cache verdict, propagation hop, commit lag and
        # the critical-path top contributors for every recent height
        out = node.block_scorecard(last=args.last or None)
        print(json.dumps(out, indent=1 if args.pretty else None))
    elif args.query_cmd == "host-profile":
        out = node.host_profile(top=args.top, folded=args.folded)
        if args.out:
            Path(args.out).write_text(
                "\n".join(
                    f"{stack} {count}"
                    for stack, count in sorted(
                        out.get("folded", {}).items(),
                        key=lambda kv: (-kv[1], kv[0]),
                    )
                )
                + "\n"
            )
        print(json.dumps({
            "node_id": out.get("node_id", ""),
            "stats": out.get("stats", {}),
            "top_frames": out.get("top_frames", []),
            **({"written": args.out} if args.out
               else {"folded": out.get("folded", {})}),
        }, indent=1))
    elif args.query_cmd == "incidents":
        print(json.dumps(node.flight_list(), indent=1))
    elif args.query_cmd == "incident":
        out = node.flight_fetch(args.id)
        if not out.get("found"):
            print(json.dumps(out))
            return 1
        if args.out:
            written = _write_bundle_files(Path(args.out), out)
            print(json.dumps({
                "id": out["manifest"]["id"],
                "reason": out["manifest"].get("reason", ""),
                "written": written,
            }, indent=1))
        else:
            print(json.dumps({"manifest": out["manifest"]}, indent=1))
    elif args.query_cmd == "cluster-incidents":
        # per-peer incident rollup; with --out, every bundle is pulled
        # mesh-wide into <out>/<node_id>/<incident_id>/
        clients = _cluster_clients(node, args)
        try:
            report = []
            for client in clients:
                addr = str(getattr(client, "address", ""))
                try:
                    listing = client.flight_list()
                except Exception as e:
                    report.append({"node": addr, "error": str(e)[:200]})
                    continue
                entry = {
                    "node": addr,
                    "enabled": listing.get("enabled", False),
                    "incidents": listing.get("incidents", []),
                }
                if args.out and entry["enabled"]:
                    fetched = []
                    for inc in entry["incidents"]:
                        if "error" in inc:
                            continue
                        bundle = client.flight_fetch(inc["id"])
                        if not bundle.get("found"):
                            continue
                        # peer-supplied node id: reduce to a safe slug
                        # (a hostile ".." or "/abs" must stay inside
                        # --out)
                        import re as _re

                        nid = _re.sub(
                            r"[^A-Za-z0-9_.-]+", "_",
                            str(inc.get("node_id") or addr or "node"),
                        ).strip(".") or "node"
                        fetched.extend(_write_bundle_files(
                            Path(args.out) / nid, bundle
                        ))
                    entry["written"] = fetched
                report.append(entry)
            print(json.dumps({
                "peers": report,
                "incidents_total": sum(
                    len(e.get("incidents", [])) for e in report
                ),
            }, indent=1))
        finally:
            _close_clients(clients, node)
    elif args.query_cmd == "trace-dump":
        out = node.trace_dump(last=args.last or None)
        if args.out:
            # write ONLY the Chrome trace document: the file opens in
            # Perfetto / chrome://tracing without editing
            Path(args.out).write_text(json.dumps(out.get("trace", {})))
            print(json.dumps({
                "enabled": out.get("enabled", False),
                "blocks": out.get("blocks", []),
                "written": args.out,
            }))
        else:
            print(json.dumps(out))
    elif args.query_cmd == "cluster-trace":
        # fan trace_dump + clock probes out to every peer and fold the
        # dumps into ONE Perfetto timeline: a node track per peer,
        # offsets applied, cross-node parent links as flow arrows
        from celestia_tpu.node import cluster as cluster_mod
        from celestia_tpu.utils.tracing import validate_chrome_trace

        clients = _cluster_clients(node, args)
        try:
            merged = cluster_mod.cluster_trace(
                clients, last=args.last or None
            )
        finally:
            _close_clients(clients, node)
        problems = validate_chrome_trace(merged)
        if problems:
            raise SystemExit(f"cluster-trace: invalid merge: {problems[:5]}")
        Path(args.out).write_text(json.dumps(merged))
        print(json.dumps({
            "written": args.out,
            "nodes": [n["node_id"] for n in merged["otherData"]["nodes"]],
            "events": len(merged["traceEvents"]),
            "cross_node_flows": merged["otherData"]["cross_node_flows"],
        }))
    elif args.query_cmd == "cluster-health":
        # coordinator-side aggregated health: per-peer height, breaker
        # states, cache hit rates, degradation/shed counts, RPC traffic
        from celestia_tpu.node import cluster as cluster_mod

        clients = _cluster_clients(node, args)
        try:
            print(json.dumps(cluster_mod.cluster_health(clients), indent=1))
        finally:
            _close_clients(clients, node)
    elif args.query_cmd == "namespace-shares":
        # fetch + VERIFY all shares of a namespace like a rollup would
        from celestia_tpu.da import namespace_data as nsd_mod
        from celestia_tpu.da.dah import DataAvailabilityHeader

        out = node.abci_query(
            "custom/namespace/shares",
            {"height": args.height, "namespace": args.namespace},
        )
        rows = tuple(bytes.fromhex(r) for r in out["dah"]["row_roots"])
        cols = tuple(bytes.fromhex(c) for c in out["dah"]["col_roots"])
        dah = DataAvailabilityHeader(
            rows, cols, DataAvailabilityHeader.compute_hash(rows, cols)
        )
        result = nsd_mod.NamespaceData.from_dict(out["data"])
        # trust anchor: the block header's recorded data root, NOT the
        # query response; and the response must answer for the namespace
        # that was ASKED (a self-consistent answer for a different
        # namespace or block must not print verified)
        trusted_root = bytes.fromhex(node.block(int(args.height))["data_root"])
        verified = (
            result.namespace == bytes.fromhex(args.namespace)
            and dah.hash == trusted_root
            and result.verify(dah)
        )
        print(json.dumps({
            "verified": verified,
            "rows": len(result.rows),
            "shares": sum(len(r.shares) for r in result.rows),
            "payload_hex": result.blobs_payload().hex() if verified else "",
        }))
    elif args.query_cmd == "blobstream":
        if args.bs_cmd == "attestation":
            print(json.dumps(node.abci_query(
                "custom/blobstream/attestation", {"nonce": args.nonce}
            )))
        elif args.bs_cmd == "nonce":
            print(json.dumps(node.abci_query(
                "custom/blobstream/latest_nonce", {}
            )))
        elif args.bs_cmd == "range":
            print(json.dumps(node.abci_query(
                "custom/blobstream/data_commitment_range",
                {"height": args.height},
            )))
        elif args.bs_cmd == "verify":
            # client/verify.go VerifyShares parity: prove the shares are
            # covered by a DataCommitment, verifying every link locally
            from celestia_tpu.client.blobstream import (
                BlobstreamVerifyError,
                verify_shares,
            )

            try:
                v = verify_shares(
                    node, int(args.height), int(args.start), int(args.end)
                )
            except BlobstreamVerifyError as e:
                print(json.dumps({"verified": False, "reason": str(e)}))
                return 1
            print(json.dumps({
                "verified": True,
                "height": v.height,
                "data_root": v.data_root.hex(),
                "nonce": v.nonce,
                "begin_block": v.begin_block,
                "end_block": v.end_block,
                "tuple_root": v.tuple_root.hex(),
            }))
    elif args.query_cmd == "das-sample":
        # fetch + VERIFY n random samples like a light client would;
        # the whole draw rides the vectorized serving plane by default
        # (ONE DasSampleBatch stream against a remote node, one
        # row-grouped batch query in-process) — --per-cell keeps the
        # scalar path for comparison/debugging
        from celestia_tpu.da import das as das_mod

        blk = node.block(int(args.height))
        lc = das_mod.LightClient(
            bytes.fromhex(blk["data_root"]), int(blk["square_size"]),
            seed=int(args.seed),
        )

        def fetch(r, c):
            out = node.abci_query(
                "custom/das/sample",
                {"height": args.height, "row": r, "col": c},
            )
            return das_mod.SampleProof.from_dict(out["proof"])

        def fetch_batch(coords):
            if hasattr(node, "das_sample_batch"):
                out = node.das_sample_batch(int(args.height), coords)
            else:
                out = node.abci_query(
                    "custom/das/sample_batch",
                    {
                        "height": args.height,
                        "coords": [[r, c] for r, c in coords],
                    },
                )
            return [
                das_mod.SampleProof.from_dict(d) for d in out["proofs"]
            ]

        if getattr(args, "per_cell", False):
            result = lc.sample(fetch, int(args.samples))
        else:
            result = lc.sample(
                fetch_batch=fetch_batch, n_samples=int(args.samples)
            )
        print(json.dumps({
            "available": result.available,
            "verified": result.verified,
            "confidence": round(result.confidence, 6),
            "failed": [
                {"row": r, "col": c, "reason": why}
                for r, c, why in result.failed
            ],
        }))
    return 0


def _write_bundle_files(out_dir: Path, bundle: dict) -> list:
    """Write one fetched incident bundle (FlightFetch shape) under
    ``out_dir/<incident_id>/`` — manifest + every artifact, exactly the
    on-disk layout the recorder keeps.  Returns the written paths.

    Bundles arrive from REMOTE peers (cluster-incidents walks the PEX
    mesh), so nothing in them is trusted: the incident id must match
    the recorder's own id grammar (a hostile "../x" or absolute id
    would otherwise escape --out via the Path join), and artifact
    names must be bare basenames."""
    from celestia_tpu.utils.flight import _ID_RE

    incident_id = str(bundle["manifest"]["id"])
    if not _ID_RE.match(incident_id):
        raise SystemExit(
            f"refusing to write bundle with hostile incident id "
            f"{incident_id!r}"
        )
    dest = out_dir / incident_id
    dest.mkdir(parents=True, exist_ok=True)
    written = []
    mpath = dest / "manifest.json"
    mpath.write_text(json.dumps(bundle["manifest"], indent=1, sort_keys=True))
    written.append(str(mpath))
    for name, text in sorted(bundle.get("files", {}).items()):
        # artifact names come from the server; never let a hostile one
        # escape the destination directory
        safe = os.path.basename(name)
        if not safe or safe != name:
            continue
        fpath = dest / safe
        fpath.write_text(text)
        written.append(str(fpath))
    return written


def _cluster_clients(seed, args):
    """Clients for a cluster-wide query: the explicit --nodes list, or
    the seed --node plus every peer its PEX surface reports."""
    from celestia_tpu.client.remote import RemoteNode
    from celestia_tpu.node import cluster as cluster_mod

    timeout = getattr(args, "timeout", 120.0)
    nodes = getattr(args, "nodes", None)
    if nodes:
        addrs = [a.strip() for a in nodes.split(",") if a.strip()]
    else:
        addrs = [args.node] + cluster_mod.discover_peers(seed)
    clients, seen = [], set()
    for addr in addrs:
        if addr in seen:
            continue
        seen.add(addr)
        if addr == args.node:
            clients.append(seed)
            continue
        try:
            clients.append(RemoteNode(addr, timeout_s=timeout))
        except Exception as e:
            print(
                json.dumps({"unreachable": addr, "error": str(e)[:120]}),
                file=sys.stderr,
            )
    return clients


def _close_clients(clients, keep) -> None:
    for c in clients:
        if c is not keep:
            c.close()


def cmd_status(args) -> int:
    print(json.dumps(_remote(args).status()))
    return 0


def cmd_coordinator(args) -> int:
    from celestia_tpu.client.remote import RemoteNode
    from celestia_tpu.node.coordinator import PeerValidator, ProcessCoordinator

    peers = [
        PeerValidator(name=f"val-{i}", client=RemoteNode(addr, timeout_s=args.timeout))
        for i, addr in enumerate(args.peers.split(","))
    ]
    coord = ProcessCoordinator(
        peers, block_interval_ns=int(args.block_interval * 1e9)
    )
    produced = 0
    while args.blocks == 0 or produced < args.blocks:
        t0 = time.time()
        coord.produce_block()
        blk = coord.blocks[-1]
        print(
            json.dumps(
                {
                    "height": blk["height"],
                    "proposer": blk["proposer"],
                    "txs": blk["n_txs"],
                    "app_hash": blk["app_hash"].hex()[:16],
                }
            ),
            flush=True,
        )
        produced += 1
        remaining = args.block_interval - (time.time() - t0)
        if remaining > 0 and (args.blocks == 0 or produced < args.blocks):
            # celint: allow(sanctioned-retry) — block-interval pacing: sleep the remainder of the slot, not a retry
            time.sleep(remaining)
    return 0


def cmd_bft_relay(args) -> int:
    from celestia_tpu.client.remote import RemoteNode
    from celestia_tpu.node.coordinator import BFTRelay, PeerValidator
    from celestia_tpu.utils import faults

    peers = [
        PeerValidator(name=f"val-{i}", client=RemoteNode(addr, timeout_s=args.timeout))
        for i, addr in enumerate(args.peers.split(","))
    ]
    relay = BFTRelay(peers)
    produced = 0
    while args.blocks == 0 or produced < args.blocks:
        t0 = time.time()
        height = relay.produce_block()
        app_hash = ""
        for peer in peers:
            try:
                app_hash = peer.client.status().get("app_hash", "")
                break
            except Exception as e:
                faults.note("relay.status", e)
                continue
        print(
            json.dumps({"height": height, "app_hash": app_hash[:16]}),
            flush=True,
        )
        produced += 1
        remaining = args.block_interval - (time.time() - t0)
        if remaining > 0 and (args.blocks == 0 or produced < args.blocks):
            # celint: allow(sanctioned-retry) — block-interval pacing: sleep the remainder of the slot, not a retry
            time.sleep(remaining)
    return 0


def cmd_snapshot(args) -> int:
    from celestia_tpu.node.snapshots import SnapshotStore

    store = SnapshotStore(str(Path(_home(args)) / "data" / "snapshots"))
    if args.snap_cmd == "list":
        for info in store.list():
            print(
                json.dumps(
                    {
                        "height": info.height,
                        "chunks": info.chunks,
                        "app_hash": info.app_hash.hex(),
                        "app_version": info.app_version,
                    }
                )
            )
    elif args.snap_cmd == "info":
        for info in store.list():
            if info.height == args.height:
                meta = store.load_state(info)
                print(
                    json.dumps(
                        {
                            "height": info.height,
                            "chain_id": info.chain_id,
                            "stores": sorted(meta["state"]),
                            "app_hash": info.app_hash.hex(),
                        }
                    )
                )
                return 0
        raise SystemExit(f"no snapshot at height {args.height}")
    return 0


def _gentx_sign_doc(decl: dict, chain_id: str) -> bytes:
    """Canonical bytes a gentx signature covers (sorted-key JSON of the
    declaration + chain id) — collect verifies the operator actually
    holds the validator key they are declaring."""
    import hashlib

    doc = dict(decl)
    doc.pop("signature", None)
    doc["chain_id"] = chain_id
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()
    ).digest()


def cmd_gentx(args) -> int:
    """``gentx`` (cmd/root.go:131-142 genesis-ceremony role): declare
    THIS home's validator for a multi-party genesis — a signed JSON the
    coordinator-less collect-gentxs step verifies and merges."""
    from celestia_tpu.utils.secp256k1 import PrivateKey

    home = Path(_home(args))
    key_file = home / "config" / "priv_validator_key.json"
    genesis_file = home / "config" / "genesis.json"
    if not key_file.exists() or not genesis_file.exists():
        raise SystemExit(f"{home} is not initialised (run init first)")
    if args.power <= 0 or args.self_delegation <= 0:
        # fail where the value originates, not at the remote collector
        raise SystemExit("--power and --self-delegation must be > 0")
    key = PrivateKey(
        int(json.loads(key_file.read_text())["priv_key"], 16)
    )
    chain_id = json.loads(genesis_file.read_text())["chain_id"]
    addr = key.public_key().address()
    decl = {
        "address": addr.hex(),
        "pubkey": key.public_key().compressed().hex(),
        "power": args.power,
        "self_delegation": args.self_delegation,
        "moniker": args.moniker,
    }
    decl["signature"] = key.sign(_gentx_sign_doc(decl, chain_id)).hex()
    out_dir = home / "config" / "gentx"
    out_dir.mkdir(exist_ok=True)
    out = out_dir / f"gentx-{addr.hex()}.json"
    out.write_text(json.dumps(decl, indent=1))
    print(json.dumps({"gentx": str(out), "address": addr.hex()}))
    return 0


def cmd_collect_gentxs(args) -> int:
    """``collect-gentxs``: verify every gentx in --gentx-dir and merge
    the declared validators (+ funding accounts + the BFT valset file)
    into this home's genesis.json — a multi-party genesis without the
    coordinator harness."""
    from celestia_tpu.utils.secp256k1 import PublicKey

    home = Path(_home(args))
    genesis_file = home / "config" / "genesis.json"
    genesis = json.loads(genesis_file.read_text())
    chain_id = genesis["chain_id"]
    gentx_dir = Path(args.gentx_dir) if args.gentx_dir else (
        home / "config" / "gentx"
    )
    files = sorted(gentx_dir.glob("gentx-*.json"))
    if not files:
        raise SystemExit(f"no gentx-*.json files in {gentx_dir}")
    validators = {v["address"]: v for v in genesis.get("validators", [])}
    accounts = {a["address"]: a for a in genesis.get("accounts", [])}
    valset: dict = {}
    for path in files:
        decl = json.loads(path.read_text())
        pub = PublicKey.from_compressed(bytes.fromhex(decl["pubkey"]))
        if pub.address().hex() != decl["address"]:
            raise SystemExit(f"{path.name}: address does not match pubkey")
        if not pub.verify(
            _gentx_sign_doc(decl, chain_id),
            bytes.fromhex(decl["signature"]),
        ):
            raise SystemExit(f"{path.name}: invalid gentx signature")
        if int(decl["power"]) <= 0 or int(decl["self_delegation"]) <= 0:
            raise SystemExit(f"{path.name}: power/self_delegation must be > 0")
        addr = decl["address"]
        vs_entry = {
            "address": addr,
            "pubkey": decl["pubkey"],
            "power": int(decl["power"]),
        }
        # two GENTXS for one address must agree exactly; a gentx freely
        # OVERRIDES a base-genesis validator entry for its own address
        # (the signature proves the signer owns that validator key, so
        # they are the authority over their own declaration — e.g. the
        # placeholder init_home seeds for the home's key)
        if addr in valset and valset[addr] != vs_entry:
            raise SystemExit(
                f"{path.name}: conflicts with another gentx for {addr}"
            )
        valset[addr] = vs_entry
        validators[addr] = {
            "address": addr,
            "self_delegation": int(decl["self_delegation"]),
        }
        # fund the account with the bond plus a liquid buffer: InitChain
        # bonds the whole self-delegation, and a validator with zero
        # spendable balance could not pay its first fee
        accounts.setdefault(
            addr,
            {
                "address": addr,
                "balance": int(decl["self_delegation"]) + 1_000_000_000,
            },
        )
    genesis["validators"] = sorted(
        validators.values(), key=lambda v: v["address"]
    )
    genesis["accounts"] = sorted(
        accounts.values(), key=lambda a: a["address"]
    )
    genesis_file.write_text(json.dumps(genesis, indent=1))
    valset_file = home / "config" / "valset.json"
    valset_file.write_text(
        json.dumps(
            sorted(valset.values(), key=lambda v: v["address"]), indent=1
        )
    )
    print(
        json.dumps(
            {
                "genesis": str(genesis_file),
                "valset": str(valset_file),
                "validators": len(valset),
            }
        )
    )
    return 0


def _genesis_errors(genesis: dict) -> list:
    """Structural checks + the decisive scratch InitChain — shared by
    validate-genesis and download-genesis."""
    errors = []
    if not isinstance(genesis.get("chain_id"), str) or not genesis["chain_id"]:
        errors.append("chain_id must be a non-empty string")
    codec = genesis.get("codec")
    if codec is not None:
        from celestia_tpu.ops import gf256

        if codec not in gf256.CODECS:
            errors.append(f"unknown codec {codec!r} (expected {gf256.CODECS})")
    seen = set()
    for i, acc in enumerate(genesis.get("accounts", [])):
        try:
            addr = bytes.fromhex(acc["address"])
            if len(addr) != 20:
                errors.append(f"accounts[{i}]: address must be 20 bytes")
            if addr in seen:
                errors.append(f"accounts[{i}]: duplicate address")
            seen.add(addr)
            if int(acc["balance"]) < 0:
                errors.append(f"accounts[{i}]: negative balance")
        except (KeyError, ValueError, TypeError) as e:
            errors.append(f"accounts[{i}]: {e}")
    seen = set()
    for i, val in enumerate(genesis.get("validators", [])):
        try:
            addr = bytes.fromhex(val["address"])
            if len(addr) != 20:
                errors.append(f"validators[{i}]: address must be 20 bytes")
            if addr in seen:
                errors.append(f"validators[{i}]: duplicate validator")
            seen.add(addr)
            if int(val["self_delegation"]) <= 0:
                errors.append(f"validators[{i}]: self_delegation must be > 0")
        except (KeyError, ValueError, TypeError) as e:
            errors.append(f"validators[{i}]: {e}")
    if not errors:
        from celestia_tpu.ops import gf256
        from celestia_tpu.state.app import App

        prev_codec = gf256.active_codec()
        try:
            App(chain_id=genesis.get("chain_id", "x")).init_chain(genesis)
        except Exception as e:
            errors.append(f"InitChain rejected the genesis: {e}")
        finally:
            # deliberate restore of a temporary switch — exempt from the
            # pin-once-at-genesis guard
            gf256.set_active_codec(prev_codec, force=True)
    return errors


def cmd_download_genesis(args) -> int:
    """``download-genesis``: fetch the chain's genesis document from a
    running peer over gRPC and install it into this home (the
    reference's download-genesis role, cmd/root.go:131-142).  The doc is
    validated with a scratch InitChain before anything is written; for a
    real deployment cross-check the chain id out of band — one serving
    peer is not a trust anchor."""
    from celestia_tpu.client.remote import RemoteNode

    home = Path(_home(args))
    cfg_dir = home / "config"
    if not cfg_dir.exists():
        raise SystemExit(f"{home} is not initialised (run init first)")
    blocks_log = home / "data" / "blocks.log"
    if (
        not args.force
        and blocks_log.exists()
        and blocks_log.stat().st_size > 0
    ):
        # replacing the genesis under an existing chain's data dir would
        # pair one chain's blocks with another's genesis on next start
        raise SystemExit(
            f"{home} already holds chain data ({blocks_log}); refusing to "
            "replace its genesis — use --force after clearing data/"
        )
    cli = RemoteNode(args.node, timeout_s=args.timeout)
    try:
        doc = cli.genesis()
    finally:
        cli.close()
    if not doc:
        raise SystemExit(f"{args.node} does not serve a genesis document")
    errors = _genesis_errors(doc)
    if errors:
        raise SystemExit(
            "downloaded genesis is invalid: " + "; ".join(errors)
        )
    (cfg_dir / "genesis.json").write_text(json.dumps(doc, indent=1))
    print(
        json.dumps(
            {"genesis": str(cfg_dir / "genesis.json"),
             "chain_id": doc.get("chain_id")}
        )
    )
    return 0


def cmd_migrate_genesis(args) -> int:
    """``migrate-genesis``: bring an older genesis file to the current
    shape.  A file without a codec key is AMBIGUOUS (chains started
    before ADR-012 ran lagrange; files generated by a post-ADR-012
    ``init`` that predates the explicit key ran leopard), so the
    operator must state which chain the file belongs to via
    ``--assume-codec`` — guessing could silently flip the consensus
    codec.  Ordering is canonicalized, the result is validated with the
    same gate as validate-genesis, and an unset genesis time is
    reported (it cannot be invented for an existing chain)."""
    from celestia_tpu.ops import gf256

    path = Path(args.file) if args.file else (
        Path(_home(args)) / "config" / "genesis.json"
    )
    try:
        genesis = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        raise SystemExit(f"cannot read genesis {path}: {e}")
    applied = []
    if "codec" not in genesis:
        if not args.assume_codec:
            raise SystemExit(
                "genesis has no codec key; state the chain's codec with "
                f"--assume-codec {{{', '.join(gf256.CODECS)}}} "
                "(pre-ADR-012 chains ran lagrange-gf256; post-ADR-012 "
                "inits without the key ran leopard-ff8)"
            )
        if args.assume_codec not in gf256.CODECS:
            raise SystemExit(f"unknown codec {args.assume_codec!r}")
        genesis["codec"] = args.assume_codec
        applied.append(f"pinned codec {args.assume_codec}")
    try:
        for section in ("accounts", "validators"):
            entries = genesis.get(section)
            if not entries:
                continue
            ordered = sorted(entries, key=lambda e: e["address"])
            if entries != ordered:
                genesis[section] = ordered
                applied.append(f"canonicalized {section} order")
    except (KeyError, TypeError) as e:
        raise SystemExit(f"malformed {section} entry: {e}")
    warnings = []
    if not genesis.get("genesis_time_ns"):
        warnings.append(
            "genesis_time_ns is unset/zero: supply the chain's original "
            "time or nodes will substitute their own wall clock"
        )
    errors = _genesis_errors(genesis)
    out_path = Path(args.output) if args.output else path
    if not errors:
        out_path.write_text(json.dumps(genesis, indent=1))
    print(
        json.dumps(
            {"output": str(out_path) if not errors else None,
             "applied": applied, "warnings": warnings, "errors": errors}
        )
    )
    return 0 if not errors else 1


def cmd_validate_genesis(args) -> int:
    """``validate-genesis``: structural checks with precise messages,
    then the decisive one — a scratch in-memory App actually runs
    InitChain on the file (what the reference's validate-genesis
    ultimately guards: will every node accept this genesis?)."""
    path = Path(args.file) if args.file else (
        Path(_home(args)) / "config" / "genesis.json"
    )
    try:
        genesis = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        print(json.dumps({"valid": False, "errors": [f"unreadable: {e}"]}))
        return 1
    errors = _genesis_errors(genesis)
    print(json.dumps({"valid": not errors, "errors": errors}))
    return 0 if not errors else 1


def cmd_txsim(args) -> int:
    """Load generator against a running node (test/cmd/txsim parity)."""
    from celestia_tpu.client.signer import Signer
    from celestia_tpu.client import txsim

    node = _remote(args)
    master = Signer(node, _load_key(_home(args), getattr(args, "from_key")))
    sequences = []
    for _ in range(args.blob):
        seq = txsim.BlobSequence(size_max=args.blob_size_max)
        if args.blob_size_max < seq.size_min:
            raise SystemExit(
                f"--blob-size-max {args.blob_size_max} is below the minimum "
                f"blob size {seq.size_min}"
            )
        sequences.append(seq)
    for _ in range(args.send):
        sequences.append(txsim.SendSequence())
    if not sequences:
        raise SystemExit("nothing to do: pass --blob N and/or --send N")
    results = txsim.run_remote(
        node, master, sequences,
        iterations=args.iterations, seed=args.seed, funding=args.funding,
    )
    ok = sum(1 for r in results if r.get("code") == 0)
    print(
        json.dumps(
            {
                "submitted": len(results),
                "succeeded": ok,
                "failed": len(results) - ok,
                "final_height": node.height,
            }
        )
    )
    return 0 if ok == len(results) else 1


def cmd_blocktime(args) -> int:
    """Average block interval over a height range (tools/blocktime)."""
    node = _remote(args)
    last = args.to_height or node.height
    first = max(2, args.from_height)
    if last <= first:
        raise SystemExit("need at least two blocks in range")
    t0 = node.block(first - 1)["time_ns"]
    t1 = node.block(last)["time_ns"]
    avg_s = (t1 - t0) / (last - first + 1) / 1e9
    print(
        json.dumps(
            {"from": first, "to": last, "avg_block_time_s": round(avg_s, 3)}
        )
    )
    return 0


# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="celestia-tpu")
    p.add_argument("--home", default=None, help="node home directory")
    p.add_argument(
        "--cpu-threads", type=int, default=None, metavar="N",
        help="host worker threads for the CPU DA pipeline (native "
             "NMT/SHA hashing, erasure decode, repair fallback); "
             "default: CELESTIA_TPU_CPU_THREADS or os.cpu_count()",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="initialise a node home")
    sp.add_argument("--chain-id", default="celestia-tpu-1")
    sp.add_argument("--overwrite", action="store_true")
    sp.add_argument(
        "--fund-keyring", type=int, default=0, metavar="UTIA",
        help="fund every key already in the home keyring with this balance",
    )
    sp.add_argument(
        "--genesis", default=None, metavar="FILE",
        help="use this shared genesis.json instead of generating one "
             "(multi-validator setups: every home gets the same genesis)",
    )
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node + gRPC service")
    sp.add_argument("--grpc-address", default=None)
    sp.add_argument("--block-interval", type=float, default=None)
    sp.add_argument("--v2-upgrade-height", type=int, default=None)
    sp.add_argument(
        "--validator", action="store_true",
        help="validator mode: no self-production; an external coordinator "
             "drives consensus through the ConsPrepare/Process/Commit RPCs",
    )
    sp.add_argument(
        "--bft-valset", default=None,
        help="two-phase BFT mode: path to the validator-set JSON "
             '([{"address","pubkey","power"}]); this node prevotes/'
             "precommits with its key and commits only on a 2/3 quorum "
             "it verified itself (a bft-relay shuttles messages)",
    )
    sp.add_argument(
        "--peers", default=None,
        help="p2p gossip mesh (with --bft-valset): comma-separated peer "
             "validator gRPC addresses; consensus messages flood "
             "directly between validators with own round timers — no "
             "relay needed",
    )
    sp.add_argument(
        "--warm-squares", default="1,2,4",
        help="square sizes whose device programs compile at boot instead "
             "of stalling the first live block ('' disables); on a "
             "mesh-active node the mesh-eligible sizes also warm the "
             "sharded programs (size up, e.g. 64,128, for full coverage)",
    )
    sp.add_argument(
        "--mesh", default=None, metavar="SPEC",
        help="multi-chip mesh factoring for the sharded extension path: "
             "'DATAxROW' (e.g. 2x4), 'auto' (default: all devices on the "
             "row axis when >1 accelerator is visible), or 'off' "
             "(CELESTIA_TPU_MESH is equivalent; the flag wins)",
    )
    sp.add_argument(
        "--trace", action="store_true",
        help="enable block-lifecycle span tracing (ring-buffered last-N "
             "blocks, served by the TraceDump RPC as Perfetto-compatible "
             "Chrome trace JSON; CELESTIA_TPU_TRACE=1 is equivalent)",
    )
    sp.add_argument(
        "--trace-blocks", type=int, default=None, metavar="N",
        help="how many recent block traces the ring keeps (default 8; "
             "CELESTIA_TPU_TRACE_BLOCKS is equivalent)",
    )
    sp.add_argument(
        "--metrics-port", type=int, default=None, metavar="PORT",
        help="serve the Prometheus exposition as plain HTTP GET /metrics "
             "on this port (0 = ephemeral; off by default — the Metrics "
             "RPC keeps serving either way)",
    )
    sp.add_argument(
        "--device-profile", nargs="?", const="", default=None, metavar="DIR",
        help="capture a jax.profiler (TensorBoard/XPlane) device trace "
             "into DIR (default: <home>/data/device-profile) for the "
             "node's lifetime; degrades to a logged note without a "
             "capturable device",
    )
    sp.add_argument(
        "--timeseries-interval", type=float, default=5.0, metavar="SECONDS",
        help="continuous-telemetry snapshot cadence for the TimeSeries "
             "ring + alert engine (0 disables the sampler; the RPC "
             "still samples on demand)",
    )
    sp.add_argument(
        "--host-profile", nargs="?", const=-1.0, type=float, default=None,
        metavar="HZ",
        help="continuous host profiling: sample every thread's stack at "
             "HZ (default rate when given bare; 0 disables), joined to "
             "live spans and served by the HostProfile RPC; folded "
             "stacks + Chrome sample events land in flight bundles "
             "(CELESTIA_TPU_HOST_PROFILE is equivalent)",
    )
    sp.add_argument(
        "--flight-dir", default=None, metavar="DIR",
        help="arm the anomaly flight recorder: alert firing transitions "
             "(and slow blocks over CELESTIA_TPU_FLIGHT_SLOW_BLOCK_MS) "
             "dump a bounded incident bundle (trace + timeseries + "
             "metrics + folded stacks + fault notes) into a size-capped "
             "ring of dirs under DIR, served by FlightList/FlightFetch",
    )
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser(
        "coordinator", help="drive consensus across validator processes"
    )
    sp.add_argument("--peers", required=True,
                    help="comma-separated validator gRPC addresses")
    sp.add_argument("--blocks", type=int, default=0,
                    help="produce N blocks then exit (0 = run forever)")
    sp.add_argument("--block-interval", type=float, default=1.0)
    sp.add_argument("--timeout", type=float, default=120.0)
    sp.set_defaults(fn=cmd_coordinator)

    sp = sub.add_parser(
        "bft-relay",
        help="dumb message transport for two-phase BFT validator "
             "processes (forwards gossip + echoes timeouts; never "
             "sequences commits)",
    )
    sp.add_argument("--peers", required=True,
                    help="comma-separated validator gRPC addresses")
    sp.add_argument("--blocks", type=int, default=0,
                    help="relay N blocks then exit (0 = run forever)")
    sp.add_argument("--block-interval", type=float, default=1.0)
    sp.add_argument("--timeout", type=float, default=120.0)
    sp.set_defaults(fn=cmd_bft_relay)

    sp = sub.add_parser("keys", help="manage the file keyring")
    ks = sp.add_subparsers(dest="keys_cmd", required=True)
    ka = ks.add_parser("add")
    ka.add_argument("name")
    ks.add_parser("list")
    kw = ks.add_parser("show")
    kw.add_argument("name")
    sp.set_defaults(fn=cmd_keys)

    sp = sub.add_parser("tx", help="sign + broadcast transactions")
    sp.add_argument("--node", default="127.0.0.1:9090")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="per-RPC timeout in seconds")
    sp.add_argument("--from", dest="from_key", required=True)
    sp.add_argument("--no-confirm", action="store_true")
    ts = sp.add_subparsers(dest="tx_cmd", required=True)
    t1 = ts.add_parser("send")
    t1.add_argument("to")
    t1.add_argument("amount")
    t2 = ts.add_parser("pay-for-blob")
    t2.add_argument("namespace", help="hex user namespace (<=10 bytes)")
    t2.add_argument("data", help="hex blob data, or @file")
    t3 = ts.add_parser("delegate")
    t3.add_argument("validator")
    t3.add_argument("amount")
    t3 = ts.add_parser("undelegate")
    t3.add_argument("validator")
    t3.add_argument("amount")
    t3 = ts.add_parser("withdraw-rewards")
    t3.add_argument("validator")
    ts.add_parser("withdraw-commission")
    t3 = ts.add_parser("fund-community-pool")
    t3.add_argument("amount")
    t3 = ts.add_parser("grant-allowance")
    t3.add_argument("grantee")
    t3.add_argument("--spend-limit", default=0)
    t3.add_argument("--expiration-ns", default=0)
    t3.add_argument("--period-ns", default=0)
    t3.add_argument("--period-spend-limit", default=0)
    t3 = ts.add_parser("revoke-allowance")
    t3.add_argument("grantee")
    t3 = ts.add_parser("authz-grant")
    t3.add_argument("grantee")
    t3.add_argument("msg_type", help="numeric Msg TYPE id to authorize")
    t3.add_argument("--spend-limit", default=0)
    t3.add_argument("--expiration-ns", default=0)
    ts.add_parser("unjail")
    sp.set_defaults(fn=cmd_tx)

    sp = sub.add_parser("query", help="query node state")
    sp.add_argument("--node", default="127.0.0.1:9090")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="per-RPC timeout in seconds")
    qs = sp.add_subparsers(dest="query_cmd", required=True)
    q = qs.add_parser("balance")
    q.add_argument("address")
    q = qs.add_parser("account")
    q.add_argument("address")
    q = qs.add_parser("tx")
    q.add_argument("hash")
    q = qs.add_parser("txs", help="search txs by indexed event")
    q.add_argument("--event", required=True,
                   help='e.g. "transfer" or "transfer.recipient=<hex>"')
    q = qs.add_parser("state-proof", help="verified state query")
    q.add_argument("store")
    q.add_argument("key", help="raw store key, hex")
    q.add_argument("--height", type=int, default=0)
    q = qs.add_parser("block")
    q.add_argument("height")
    q = qs.add_parser("param")
    q.add_argument("subspace")
    q.add_argument("key")
    q = qs.add_parser("share-proof")
    q.add_argument("height", type=int)
    q.add_argument("start", type=int)
    q.add_argument("end", type=int)
    q = qs.add_parser("tx-proof")
    q.add_argument("height", type=int)
    q.add_argument("index", type=int)
    q = qs.add_parser("rewards")
    q.add_argument("delegator")
    q.add_argument("validator")
    qs.add_parser("community-pool")
    q = qs.add_parser("signing-info")
    q.add_argument("validator")
    qs.add_parser("invariants")
    qs.add_parser("metrics", help="node Prometheus text exposition")
    q = qs.add_parser(
        "timeseries",
        help="continuous-telemetry snapshots + per-metric rates "
             "(the bounded TimeSeries ring)",
    )
    q.add_argument("--last", type=int, default=0,
                   help="only the most recent N snapshots (0 = all kept)")
    q.add_argument("--pretty", action="store_true",
                   help="indent the JSON output")
    q = qs.add_parser(
        "alerts",
        help="declarative alert-rule verdicts over the telemetry ring "
             "(threshold / sustained-burn / rate / stall rules)",
    )
    q.add_argument("--firing-only", action="store_true",
                   help="print only the rules currently firing")
    q.add_argument("--fail-on-firing", action="store_true",
                   help="exit 1 when any rule fires (CI/automation probe)")
    q = qs.add_parser(
        "block-scorecard",
        help="per-height block scorecard: prepare/process walls, extend "
             "leg, propagation delay, commit lag, critical-path top "
             "contributors",
    )
    q.add_argument("--last", type=int, default=0,
                   help="only the most recent N heights (0 = all kept)")
    q.add_argument("--pretty", action="store_true",
                   help="indent the JSON output")
    q = qs.add_parser(
        "host-profile",
        help="the node's host sampling-profiler view: sampler stats, "
             "top self-time frames, folded stacks (flamegraph input)",
    )
    q.add_argument("--top", type=int, default=25,
                   help="how many self-time frames to report")
    q.add_argument("--folded", type=int, default=200,
                   help="how many folded stacks to include (by count)")
    q.add_argument("--out", default=None,
                   help="also write the folded stacks to this file "
                        "(one 'stack count' line each — feed it to "
                        "flamegraph.pl / speedscope)")
    q = qs.add_parser(
        "incidents",
        help="list the node's kept flight-recorder incident bundles",
    )
    q = qs.add_parser(
        "incident",
        help="fetch one incident bundle (default: the newest) and "
             "write its artifacts to --out",
    )
    q.add_argument("--id", default="",
                   help="incident id (from `query incidents`; default: "
                        "the newest bundle)")
    q.add_argument("--out", default=None,
                   help="directory to write the bundle's files into "
                        "(created; default: print the manifest only)")
    q = qs.add_parser(
        "cluster-incidents",
        help="collect flight-recorder incident lists (and, with --out, "
             "the bundles) from every peer in the mesh",
    )
    q.add_argument("--nodes", default=None,
                   help="comma-separated peer gRPC addresses (default: "
                        "--node plus its PEX-reported peers)")
    q.add_argument("--out", default=None,
                   help="directory to download every peer's bundles into "
                        "(<out>/<node_id>/<incident_id>/...)")
    q = qs.add_parser(
        "trace-dump",
        help="last N block traces as Chrome trace JSON (open in Perfetto)",
    )
    q.add_argument("--last", type=int, default=0,
                   help="only the most recent N block traces (0 = all kept)")
    q.add_argument("--out", default=None,
                   help="write the Chrome trace document to this file")
    q = qs.add_parser(
        "cluster-trace",
        help="fan trace-dump out to every peer and merge into ONE "
             "Perfetto timeline (node tracks, aligned clocks, "
             "cross-node flow links)",
    )
    q.add_argument("--nodes", default=None,
                   help="comma-separated peer gRPC addresses (default: "
                        "--node plus its PEX-reported peers)")
    q.add_argument("--last", type=int, default=0,
                   help="only the most recent N block traces per node")
    q.add_argument("--out", default="cluster.trace.json",
                   help="write the merged Chrome trace document here")
    q = qs.add_parser(
        "cluster-health",
        help="aggregated per-peer health: heights, breaker states, "
             "cache hit rates, degradation/shed counts, RPC traffic",
    )
    q.add_argument("--nodes", default=None,
                   help="comma-separated peer gRPC addresses (default: "
                        "--node plus its PEX-reported peers)")
    q = qs.add_parser("das-sample", help="light-client availability sampling")
    q.add_argument("height", type=int)
    q.add_argument("--samples", type=int, default=16)
    q.add_argument("--seed", type=int, default=0)
    q.add_argument(
        "--per-cell", action="store_true",
        help="fetch each sample with a separate DasSample RPC instead "
             "of the batched serving plane (comparison/debugging)",
    )
    q = qs.add_parser(
        "namespace-shares", help="all shares of a namespace, verified"
    )
    q.add_argument("height", type=int)
    q.add_argument("namespace", help="29-byte namespace, hex")
    q = qs.add_parser(
        "blobstream", help="EVM-bridge attestations + client verification"
    )
    bs = q.add_subparsers(dest="bs_cmd", required=True)
    b = bs.add_parser("attestation")
    b.add_argument("nonce", type=int)
    bs.add_parser("nonce")
    b = bs.add_parser("range", help="DataCommitment window covering a height")
    b.add_argument("height", type=int)
    b = bs.add_parser(
        "verify",
        help="prove shares are covered by a DataCommitment "
             "(client/verify.go VerifyShares parity)",
    )
    b.add_argument("height", type=int)
    b.add_argument("start", type=int)
    b.add_argument("end", type=int)
    sp.set_defaults(fn=cmd_query)

    sp = sub.add_parser("status", help="node status")
    sp.add_argument("--node", default="127.0.0.1:9090")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="per-RPC timeout in seconds")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser(
        "gentx", help="declare this home's validator for a shared genesis"
    )
    sp.add_argument("--self-delegation", type=int, default=100_000_000)
    sp.add_argument("--power", type=int, default=100)
    sp.add_argument("--moniker", default="")
    sp.set_defaults(fn=cmd_gentx)

    sp = sub.add_parser(
        "collect-gentxs",
        help="verify + merge gentx files into genesis.json and valset.json",
    )
    sp.add_argument(
        "--gentx-dir", default=None,
        help="directory of gentx-*.json files (default: home/config/gentx)",
    )
    sp.set_defaults(fn=cmd_collect_gentxs)

    sp = sub.add_parser(
        "validate-genesis", help="check a genesis file incl. a scratch InitChain"
    )
    sp.add_argument(
        "--file", default=None,
        help="genesis path (default: home/config/genesis.json)",
    )
    sp.set_defaults(fn=cmd_validate_genesis)

    sp = sub.add_parser(
        "download-genesis",
        help="fetch + validate the genesis document from a running peer",
    )
    sp.add_argument("--node", default="127.0.0.1:9090")
    sp.add_argument("--timeout", type=float, default=120.0)
    sp.add_argument(
        "--force", action="store_true",
        help="replace the genesis even though the home holds chain data",
    )
    sp.set_defaults(fn=cmd_download_genesis)

    sp = sub.add_parser(
        "migrate-genesis",
        help="bring an older genesis file to the current shape",
    )
    sp.add_argument("--file", default=None)
    sp.add_argument("--output", default=None,
                    help="write here instead of in place")
    sp.add_argument(
        "--assume-codec", default=None,
        help="codec to pin when the file has no codec key (required then)",
    )
    sp.set_defaults(fn=cmd_migrate_genesis)

    sp = sub.add_parser("txsim", help="transaction load generator")
    sp.add_argument("--node", default="127.0.0.1:9090")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="per-RPC timeout in seconds")
    sp.add_argument("--from", dest="from_key", required=True,
                    help="master key (funds the sub-accounts)")
    sp.add_argument("--blob", type=int, default=1, help="blob sequences")
    sp.add_argument("--send", type=int, default=0, help="send sequences")
    sp.add_argument("--iterations", type=int, default=10)
    sp.add_argument("--blob-size-max", type=int, default=10_000)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--funding", type=int, default=10**9)
    sp.set_defaults(fn=cmd_txsim)

    sp = sub.add_parser("snapshot", help="manage state-sync snapshots")
    ss = sp.add_subparsers(dest="snap_cmd", required=True)
    ss.add_parser("list")
    sr = ss.add_parser("info")
    sr.add_argument("height", type=int)
    sp.set_defaults(fn=cmd_snapshot)

    sp = sub.add_parser("blocktime", help="average block interval")
    sp.add_argument("--node", default="127.0.0.1:9090")
    sp.add_argument("--timeout", type=float, default=120.0,
                    help="per-RPC timeout in seconds")
    sp.add_argument("--from-height", type=int, default=2)
    sp.add_argument("--to-height", type=int, default=0)
    sp.set_defaults(fn=cmd_blocktime)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "cpu_threads", None) is not None:
        from celestia_tpu.utils import hostpool

        try:
            hostpool.set_cpu_threads(args.cpu_threads)
        except ValueError as e:
            raise SystemExit(f"--cpu-threads: {e}")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
