"""Multi-chip sharded block extension: shard_map over a jax.sharding.Mesh.

The TPU-native replacement for the reference's intra-block parallelism
(rsmt2d's goroutine row/col fan-out, SURVEY.md §2.3): rows of the original
square are sharded across the ``row`` mesh axis (ICI), whole squares are
batched across the ``data`` axis (multi-block validator catch-up,
BASELINE.json config #5).

Communication pattern (all XLA collectives over ICI):

* Q1 (row parity): fully local — each device encodes its own row shard.
* Q2/Q3 (column parity): the GF(2) contraction runs over the sharded row
  axis, so each device computes a partial bit-matmul against its slice of
  the encode matrix, reduced with ``psum_scatter`` so every device ends up
  holding only its shard of the parity rows (a reduce-scatter, not an
  all-reduce — 1/R the traffic).
* Row-tree NMT roots: local.  Column-tree NMT roots: each device reduces its
  local rows of every column to one subtree node, then an ``all_gather`` of
  those (tiny: R x 2k x 90 bytes) finishes the top log2(R) levels
  replicated on every device.
* Data root: row/col roots are all-gathered (2 x 2k x 90 bytes) and the
  RFC-6962 reduction is computed replicated — every device holds the same
  data root, the sharded analogue of the DAH hash at
  /root/reference/pkg/da/data_availability_header.go:92-108.
"""

from __future__ import annotations

from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from celestia_tpu.appconsts import NAMESPACE_SIZE, SHARE_SIZE
from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.ops import rs
from celestia_tpu.ops.gf256 import active_codec as _active_codec
from celestia_tpu.ops.gf256 import encode_matrix_bits
from celestia_tpu.ops.nmt import NMT_DIGEST_SIZE, _PARITY_NS
from celestia_tpu.utils import devprof, tracing
from celestia_tpu.utils.lru import LruCache


def make_mesh(devices=None, data: int = 1, row: int = None) -> Mesh:
    """Build a ("data", "row") mesh over the given (or all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if row is None:
        row = n // data
    if data * row != n:
        raise ValueError(f"data*row = {data}*{row} != device count {n}")
    arr = np.asarray(devices).reshape(data, row)
    return Mesh(arr, ("data", "row"))


def _extend_rows_local(q_top: jnp.ndarray, G: jnp.ndarray) -> jnp.ndarray:
    """Row parity for the local row shard: (r, k, B) -> (r, k, B)."""
    return rs.pack_bits(rs.matmul_gf2(G, rs.unpack_bits(q_top)))


def _sharded_extend_and_roots(square_shard: jnp.ndarray, G: jnp.ndarray, k: int,
                              n_row_shards: int):
    """shard_map body: square_shard (k/R, k, 512) local rows -> per-device
    outputs (local EDS rows slice, replicated roots + data root)."""
    R = n_row_shards
    rows_local = k // R
    shard_id = jax.lax.axis_index("row")

    # --- Q1: local row extension ------------------------------------------
    q1 = _extend_rows_local(square_shard, G)  # (k/R, k, B)
    top = jnp.concatenate([square_shard, q1], axis=1)  # (k/R, 2k, B)

    # --- Q2/Q3: column parity via sharded contraction ---------------------
    # Columns hold k values spread across the row shards; the encode matrix
    # contracts over all 8k bit-rows.  Device d multiplies its (8k/R)-slice
    # of G's columns with its local bits, then psum_scatter sums partials
    # and scatters the 8k output bit-rows back across the row axis.
    bits_local = rs.unpack_bits(top.transpose(1, 0, 2))  # (2k, 8*k/R, B)
    g_cols = jax.lax.dynamic_slice_in_dim(
        G, shard_id * (8 * rows_local), 8 * rows_local, axis=1
    )  # (8k, 8k/R)
    partial = jnp.matmul(g_cols, bits_local, preferred_element_type=jnp.int32)
    # (2k, 8k, B) partial sums; reduce-scatter over the output bit-row axis.
    partial = partial.transpose(1, 0, 2)  # (8k, 2k, B)
    summed = jax.lax.psum_scatter(partial, "row", scatter_dimension=0, tiled=True)
    bot_bits = (summed & 1).astype(jnp.int8)  # (8k/R, 2k, B)
    bot = rs.pack_bits(
        bot_bits.reshape(rows_local, 8, 2 * k, SHARE_SIZE)
        .transpose(2, 0, 1, 3)
        .reshape(2 * k, 8 * rows_local, SHARE_SIZE)
    ).transpose(1, 0, 2)  # (k/R, 2k, B) local parity rows
    # Note: psum_scatter gives contiguous slices in shard order, so device d
    # holds parity rows [d*k/R, (d+1)*k/R) — same contiguous layout as Q0.

    # --- NMT leaves with namespace prefixes --------------------------------
    # Global row indexes of this device's rows: top half r0+i, bottom half
    # k + r0 + i; Q0 membership needs global (row, col) coordinates.
    r0 = shard_id * rows_local
    col_idx = jnp.arange(2 * k)
    parity_ns = jnp.asarray(_PARITY_NS)

    def prefixed(rows, global_row_offset):
        own = rows[..., :NAMESPACE_SIZE]
        grow = global_row_offset + jnp.arange(rows.shape[0])
        in_q0 = (grow[:, None] < k) & (col_idx[None, :] < k)
        pref = jnp.where(in_q0[..., None], own, jnp.broadcast_to(parity_ns, own.shape))
        return jnp.concatenate([pref, rows], axis=-1)

    top_leaves = prefixed(top, r0)  # (k/R, 2k, 541)
    bot_leaves = prefixed(bot, k + r0)

    # --- row-tree roots: fully local ---------------------------------------
    top_row_roots = nmt_ops.nmt_roots(top_leaves)  # (k/R, 90)
    bot_row_roots = nmt_ops.nmt_roots(bot_leaves)
    row_roots = jnp.concatenate(
        [
            jax.lax.all_gather(top_row_roots, "row", axis=0, tiled=True),
            jax.lax.all_gather(bot_row_roots, "row", axis=0, tiled=True),
        ],
        axis=0,
    )  # (2k, 90) replicated

    # --- column-tree roots: local subtree reduce + gathered finish ---------
    # Column-tree leaves are ordered by global row: [top rows..., bottom
    # rows...].  Device d holds two contiguous leaf blocks per column (its Q0
    # /Q1 rows and its Q2/Q3 rows); reduce each block to one subtree node,
    # all_gather the 2R nodes per column (in global order), finish locally.
    col_leaves_top = top_leaves.transpose(1, 0, 2)  # (2k cols, k/R, 541)
    col_leaves_bot = bot_leaves.transpose(1, 0, 2)

    def reduce_block(leaves):
        nodes = nmt_ops.leaf_digests(leaves)
        while nodes.shape[-2] > 1:
            nodes = nmt_ops.combine_level(nodes)
        return nodes[..., 0, :]  # (2k, 90)

    sub_top = reduce_block(col_leaves_top)
    sub_bot = reduce_block(col_leaves_bot)
    # gather per-device subtree nodes in global row order
    g_top = jax.lax.all_gather(sub_top, "row", axis=0)  # (R, 2k, 90)
    g_bot = jax.lax.all_gather(sub_bot, "row", axis=0)
    nodes = jnp.concatenate([g_top, g_bot], axis=0)  # (2R, 2k, 90)
    nodes = nodes.transpose(1, 0, 2)  # (2k cols, 2R, 90)
    while nodes.shape[-2] > 1:
        nodes = nmt_ops.combine_level(nodes)
    col_roots = nodes[..., 0, :]  # (2k, 90) replicated

    # --- data root ----------------------------------------------------------
    all_roots = jnp.concatenate([row_roots, col_roots], axis=0)  # (4k, 90)
    data_root = nmt_ops.rfc6962_root_pow2(all_roots)  # (32,) replicated

    eds_local = jnp.concatenate([top[:, None], bot[:, None]], axis=1)
    # (k/R, 2, 2k, B): [:, 0] = top-half rows, [:, 1] = bottom-half rows
    return eds_local, row_roots, col_roots, data_root


# program-handle cache on the unified LRU (celint R2's sanctioned
# surface): one jitted shard_map program per (mesh, k, batched, codec).
# 64 entries cover every power-of-two k x 2 legs x a few factorings; an
# eviction only costs a retrace, never wrong bytes.
_FN_CACHE = LruCache("sharded_fns", 64)


def _build_sharded_fn(mesh: Mesh, k: int, batched: bool, codec: str):
    R = mesh.shape["row"]
    if k % R:
        raise ValueError(f"square size {k} not divisible by row shards {R}")
    G = jnp.asarray(encode_matrix_bits(k, codec))
    body = partial(_sharded_extend_and_roots, G=G, k=k, n_row_shards=R)

    if not batched:
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=P("row", None, None),
            out_specs=(P("row", None, None, None), P(), P(), P()),
            check_rep=False,
        )
        return jax.jit(fn)

    vbody = jax.vmap(body)
    fn = shard_map(
        vbody,
        mesh=mesh,
        in_specs=P("data", "row", None, None),
        out_specs=(
            P("data", "row", None, None, None),
            P("data"),
            P("data"),
            P("data"),
        ),
        check_rep=False,
    )
    return jax.jit(fn)


def _sharded_fn(mesh: Mesh, k: int, batched: bool, codec: str):
    key = (mesh, k, batched, codec)
    fn = _FN_CACHE.get(key)
    if fn is None:
        # built OUTSIDE the cache lock (encode_matrix_bits is real work);
        # a racing double-build puts identical handles — last writer wins
        fn = _build_sharded_fn(mesh, k, batched, codec)
        _FN_CACHE.put(key, fn)
    return fn


def _extend_and_roots_sharded_device(
    square: np.ndarray, mesh: Mesh, *, record_stats: bool = True
):
    """Sharded fused hot path on a mesh, DEVICE-RESIDENT results:
    square uint8[k,k,512] -> (eds_dev uint8[2k,2k,512], row_roots,
    col_roots, data_root) — all four still on their chips.  The
    reassembly from the (k, 2, 2k, B) row-shard layout happens with a
    device-side concatenate, so the header paths below never pull the
    shares host-side at all (da/device_plane.py contract: the only D2H
    of the proposal path is the roots).

    Instrumented: an ``extend.sharded`` span with the mesh shape as args
    (the live-path trace names the factoring) and a devprof dispatch
    bracket that records the t1→t2 interval on EVERY chip the output is
    sharded across — device occupancy across chips is a measured number
    on the merged Perfetto timeline, not a guess.  ``record_stats=False``
    keeps warm-up extends (cli boot) out of the mesh provider's
    sharded-extends counter — the exposition reports LIVE extends."""
    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    codec = _active_codec()
    data_ax, row_ax = int(mesh.shape["data"]), int(mesh.shape["row"])
    with tracing.span(
        "extend.sharded", k=k, mesh_data=data_ax, mesh_row=row_ax,
        codec=codec,
    ):
        sharding = NamedSharding(mesh, P("row", None, None))
        x = jax.device_put(jnp.asarray(square), sharding)
        devprof.record_transfer("extend_sharded", "h2d", int(square.nbytes))
        fn = _sharded_fn(mesh, k, False, codec)
        d = devprof.dispatch(
            "extend_sharded", multi_device=True,
            k=k, mesh=f"{data_ax}x{row_ax}", codec=codec,
        )
        out = d.done(fn(x))
        eds_local, row_roots, col_roots, data_root = out
        # device-side reassembly of the row-shard layout (the old host
        # np.concatenate reassembly cost two PCIe crossings per square)
        eds_dev = jnp.concatenate(
            [eds_local[:, 0], eds_local[:, 1]], axis=0
        )
    # cost accounting OUTSIDE the traced span (same placement contract
    # as da/dah.py): the one-time AOT compile lands in the
    # celestia_tpu_xla_* kernel table, never in the phase ms
    devprof.note_compile("extend_sharded", fn, (x,))
    if record_stats:
        from celestia_tpu.parallel import mesh as mesh_mod

        mesh_mod.record_sharded_extend()
    return eds_dev, row_roots, col_roots, data_root


def extend_and_roots_sharded(
    square: np.ndarray, mesh: Mesh, *, record_stats: bool = True
):
    """Sharded fused hot path on a mesh: square uint8[k,k,512] ->
    (eds uint8[2k,2k,512], row_roots, col_roots, data_root) as HOST
    arrays (the legacy contract).  All four results cross in ONE
    batched ``device_get`` — callers that can keep the EDS on device
    should use :func:`extend_and_header_sharded` instead, which fetches
    only the roots."""
    eds_dev, row_roots, col_roots, data_root = (
        _extend_and_roots_sharded_device(
            square, mesh, record_stats=record_stats
        )
    )
    with tracing.span("roots", stage="fetch", sharded=True):
        return devprof.fetch(
            "sharded_results", (eds_dev, row_roots, col_roots, data_root)
        )


def _extend_and_roots_sharded_batch_device(
    squares: np.ndarray, mesh: Mesh, *, count_squares: int = None
):
    """Batched sharded path, DEVICE-RESIDENT results: uint8[n,k,k,512],
    n divisible by the data axis -> (eds_dev[n,2k,2k,512],
    row_roots[n,2k,90], col_roots[n,2k,90], data_roots[n,32]) with all
    four still on their chips (per-square reassembly is one device-side
    concatenate over the whole batch).  One device dispatch for the
    whole batch — the state-sync catch-up leg (BASELINE.json config #5).

    ``count_squares``: how many of the n inputs are REAL squares (the
    rest are data-axis padding the caller will drop) — only the real
    ones land in the mesh provider's sharded-extends counter."""
    squares = np.asarray(squares, dtype=np.uint8)
    n, k = squares.shape[0], squares.shape[1]
    codec = _active_codec()
    data_ax, row_ax = int(mesh.shape["data"]), int(mesh.shape["row"])
    with tracing.span(
        "extend.sharded", k=k, batch=n, mesh_data=data_ax,
        mesh_row=row_ax, codec=codec,
    ):
        sharding = NamedSharding(mesh, P("data", "row", None, None))
        x = jax.device_put(jnp.asarray(squares), sharding)
        devprof.record_transfer(
            "extend_sharded_batch", "h2d", int(squares.nbytes)
        )
        fn = _sharded_fn(mesh, k, True, codec)
        d = devprof.dispatch(
            "extend_sharded_batch", multi_device=True,
            k=k, batch=n, mesh=f"{data_ax}x{row_ax}", codec=codec,
        )
        out = d.done(fn(x))
        eds_local, row_roots, col_roots, data_roots = out
        # (n, k, 2, 2k, B) row-shard layout -> (n, 2k, 2k, B), on device
        eds_dev = jnp.concatenate(
            [eds_local[:, :, 0], eds_local[:, :, 1]], axis=1
        )
    devprof.note_compile("extend_sharded_batch", fn, (x,))
    from celestia_tpu.parallel import mesh as mesh_mod

    mesh_mod.record_sharded_extend(
        batched=True, squares=n if count_squares is None else count_squares
    )
    return eds_dev, row_roots, col_roots, data_roots


def extend_and_roots_sharded_batch(
    squares: np.ndarray, mesh: Mesh, *, count_squares: int = None
):
    """Batched sharded path with the legacy HOST-array contract (see
    :func:`_extend_and_roots_sharded_batch_device`): all four results
    cross in ONE batched ``device_get``."""
    eds_dev, row_roots, col_roots, data_roots = (
        _extend_and_roots_sharded_batch_device(
            squares, mesh, count_squares=count_squares
        )
    )
    with tracing.span("roots", stage="fetch", sharded=True):
        return devprof.fetch(
            "sharded_results", (eds_dev, row_roots, col_roots, data_roots)
        )


# ---------------------------------------------------------------------------
# (EDS, DAH) entries for the live proposal lifecycle (state/app.py)
# ---------------------------------------------------------------------------


def _header_from_roots(row_roots: np.ndarray, col_roots: np.ndarray,
                       data_root: np.ndarray):
    """Fold sharded root arrays into a DataAvailabilityHeader whose hash
    IS the replicated data root the mesh computed (cross-checked: the
    sharded RFC-6962 fold and the host fold agree byte-for-byte per
    tests/_sharded_isolated.py, so this trusts the device fold)."""
    from celestia_tpu.da.dah import DataAvailabilityHeader

    n2 = row_roots.shape[0]
    return DataAvailabilityHeader(
        tuple(row_roots[i].tobytes() for i in range(n2)),
        tuple(col_roots[i].tobytes() for i in range(n2)),
        np.asarray(data_root).tobytes(),
    )


def extend_and_header_sharded(square: np.ndarray, mesh: Mesh):
    """The mesh twin of da/dah.extend_and_header: square uint8[k,k,512]
    -> (ExtendedDataSquare, DataAvailabilityHeader), byte-identical to
    the single-device path (the consensus-safety requirement)."""
    from celestia_tpu.da.dah import ExtendedDataSquare

    eds_dev, row_roots, col_roots, data_root = (
        _extend_and_roots_sharded_device(square, mesh)
    )
    # only the roots cross (one batched fetch, ~4k x 90 + 32 bytes);
    # the EDS stays sharded on its chips until .shares is actually read
    rr, cc, dr = devprof.fetch(
        "sharded_roots", (row_roots, col_roots, data_root)
    )
    return ExtendedDataSquare(eds_dev), _header_from_roots(rr, cc, dr)


def extend_block_sharded(square, mesh: Mesh):
    """The mesh twin of da/dah.extend_block: a da.square.Square in, one
    sharded dispatch, (EDS, DAH) out."""
    k = square.size
    arr = square.to_array().reshape(k, k, SHARE_SIZE)
    return extend_and_header_sharded(arr, mesh)


def extend_and_headers_sharded_batch(
    squares: np.ndarray, mesh: Mesh, *, count_squares: int = None
) -> List[Tuple[object, object]]:
    """Batched (EDS, DAH) list for n same-k squares in ONE dispatch.

    The caller pads the batch to a multiple of the ``data`` axis (the
    shard_map leading dim must divide it) and drops the pad results; the
    state-sync warm path (state/app.py warm_extends_batched) does both
    and passes ``count_squares`` so pads never inflate the counter.
    """
    from celestia_tpu.da.dah import ExtendedDataSquare

    eds_dev, row_roots, col_roots, data_roots = (
        _extend_and_roots_sharded_batch_device(
            squares, mesh, count_squares=count_squares
        )
    )
    # one batched root fetch for the WHOLE warm batch; each square's
    # shares stay device-resident until someone reads them
    rr, cc, drs = devprof.fetch(
        "sharded_roots", (row_roots, col_roots, data_roots)
    )
    out: List[Tuple[object, object]] = []
    for i in range(eds_dev.shape[0]):
        out.append(
            (
                ExtendedDataSquare(eds_dev[i]),
                _header_from_roots(rr[i], cc[i], drs[i]),
            )
        )
    return out
