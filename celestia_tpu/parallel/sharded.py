"""Multi-chip sharded block extension: shard_map over a jax.sharding.Mesh.

The TPU-native replacement for the reference's intra-block parallelism
(rsmt2d's goroutine row/col fan-out, SURVEY.md §2.3): rows of the original
square are sharded across the ``row`` mesh axis (ICI), whole squares are
batched across the ``data`` axis (multi-block validator catch-up,
BASELINE.json config #5).

Communication pattern (all XLA collectives over ICI):

* Q1 (row parity): fully local — each device encodes its own row shard.
* Q2/Q3 (column parity): the GF(2) contraction runs over the sharded row
  axis, so each device computes a partial bit-matmul against its slice of
  the encode matrix, reduced with ``psum_scatter`` so every device ends up
  holding only its shard of the parity rows (a reduce-scatter, not an
  all-reduce — 1/R the traffic).
* Row-tree NMT roots: local.  Column-tree NMT roots: each device reduces its
  local rows of every column to one subtree node, then an ``all_gather`` of
  those (tiny: R x 2k x 90 bytes) finishes the top log2(R) levels
  replicated on every device.
* Data root: row/col roots are all-gathered (2 x 2k x 90 bytes) and the
  RFC-6962 reduction is computed replicated — every device holds the same
  data root, the sharded analogue of the DAH hash at
  /root/reference/pkg/da/data_availability_header.go:92-108.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from celestia_tpu.appconsts import NAMESPACE_SIZE, SHARE_SIZE
from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.ops import rs
from celestia_tpu.ops.gf256 import active_codec as _active_codec
from celestia_tpu.ops.gf256 import encode_matrix_bits
from celestia_tpu.ops.nmt import NMT_DIGEST_SIZE, _PARITY_NS


def make_mesh(devices=None, data: int = 1, row: int = None) -> Mesh:
    """Build a ("data", "row") mesh over the given (or all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if row is None:
        row = n // data
    if data * row != n:
        raise ValueError(f"data*row = {data}*{row} != device count {n}")
    arr = np.asarray(devices).reshape(data, row)
    return Mesh(arr, ("data", "row"))


def _extend_rows_local(q_top: jnp.ndarray, G: jnp.ndarray) -> jnp.ndarray:
    """Row parity for the local row shard: (r, k, B) -> (r, k, B)."""
    return rs.pack_bits(rs.matmul_gf2(G, rs.unpack_bits(q_top)))


def _sharded_extend_and_roots(square_shard: jnp.ndarray, G: jnp.ndarray, k: int,
                              n_row_shards: int):
    """shard_map body: square_shard (k/R, k, 512) local rows -> per-device
    outputs (local EDS rows slice, replicated roots + data root)."""
    R = n_row_shards
    rows_local = k // R
    shard_id = jax.lax.axis_index("row")

    # --- Q1: local row extension ------------------------------------------
    q1 = _extend_rows_local(square_shard, G)  # (k/R, k, B)
    top = jnp.concatenate([square_shard, q1], axis=1)  # (k/R, 2k, B)

    # --- Q2/Q3: column parity via sharded contraction ---------------------
    # Columns hold k values spread across the row shards; the encode matrix
    # contracts over all 8k bit-rows.  Device d multiplies its (8k/R)-slice
    # of G's columns with its local bits, then psum_scatter sums partials
    # and scatters the 8k output bit-rows back across the row axis.
    bits_local = rs.unpack_bits(top.transpose(1, 0, 2))  # (2k, 8*k/R, B)
    g_cols = jax.lax.dynamic_slice_in_dim(
        G, shard_id * (8 * rows_local), 8 * rows_local, axis=1
    )  # (8k, 8k/R)
    partial = jnp.matmul(g_cols, bits_local, preferred_element_type=jnp.int32)
    # (2k, 8k, B) partial sums; reduce-scatter over the output bit-row axis.
    partial = partial.transpose(1, 0, 2)  # (8k, 2k, B)
    summed = jax.lax.psum_scatter(partial, "row", scatter_dimension=0, tiled=True)
    bot_bits = (summed & 1).astype(jnp.int8)  # (8k/R, 2k, B)
    bot = rs.pack_bits(
        bot_bits.reshape(rows_local, 8, 2 * k, SHARE_SIZE)
        .transpose(2, 0, 1, 3)
        .reshape(2 * k, 8 * rows_local, SHARE_SIZE)
    ).transpose(1, 0, 2)  # (k/R, 2k, B) local parity rows
    # Note: psum_scatter gives contiguous slices in shard order, so device d
    # holds parity rows [d*k/R, (d+1)*k/R) — same contiguous layout as Q0.

    # --- NMT leaves with namespace prefixes --------------------------------
    # Global row indexes of this device's rows: top half r0+i, bottom half
    # k + r0 + i; Q0 membership needs global (row, col) coordinates.
    r0 = shard_id * rows_local
    col_idx = jnp.arange(2 * k)
    parity_ns = jnp.asarray(_PARITY_NS)

    def prefixed(rows, global_row_offset):
        own = rows[..., :NAMESPACE_SIZE]
        grow = global_row_offset + jnp.arange(rows.shape[0])
        in_q0 = (grow[:, None] < k) & (col_idx[None, :] < k)
        pref = jnp.where(in_q0[..., None], own, jnp.broadcast_to(parity_ns, own.shape))
        return jnp.concatenate([pref, rows], axis=-1)

    top_leaves = prefixed(top, r0)  # (k/R, 2k, 541)
    bot_leaves = prefixed(bot, k + r0)

    # --- row-tree roots: fully local ---------------------------------------
    top_row_roots = nmt_ops.nmt_roots(top_leaves)  # (k/R, 90)
    bot_row_roots = nmt_ops.nmt_roots(bot_leaves)
    row_roots = jnp.concatenate(
        [
            jax.lax.all_gather(top_row_roots, "row", axis=0, tiled=True),
            jax.lax.all_gather(bot_row_roots, "row", axis=0, tiled=True),
        ],
        axis=0,
    )  # (2k, 90) replicated

    # --- column-tree roots: local subtree reduce + gathered finish ---------
    # Column-tree leaves are ordered by global row: [top rows..., bottom
    # rows...].  Device d holds two contiguous leaf blocks per column (its Q0
    # /Q1 rows and its Q2/Q3 rows); reduce each block to one subtree node,
    # all_gather the 2R nodes per column (in global order), finish locally.
    col_leaves_top = top_leaves.transpose(1, 0, 2)  # (2k cols, k/R, 541)
    col_leaves_bot = bot_leaves.transpose(1, 0, 2)

    def reduce_block(leaves):
        nodes = nmt_ops.leaf_digests(leaves)
        while nodes.shape[-2] > 1:
            nodes = nmt_ops.combine_level(nodes)
        return nodes[..., 0, :]  # (2k, 90)

    sub_top = reduce_block(col_leaves_top)
    sub_bot = reduce_block(col_leaves_bot)
    # gather per-device subtree nodes in global row order
    g_top = jax.lax.all_gather(sub_top, "row", axis=0)  # (R, 2k, 90)
    g_bot = jax.lax.all_gather(sub_bot, "row", axis=0)
    nodes = jnp.concatenate([g_top, g_bot], axis=0)  # (2R, 2k, 90)
    nodes = nodes.transpose(1, 0, 2)  # (2k cols, 2R, 90)
    while nodes.shape[-2] > 1:
        nodes = nmt_ops.combine_level(nodes)
    col_roots = nodes[..., 0, :]  # (2k, 90) replicated

    # --- data root ----------------------------------------------------------
    all_roots = jnp.concatenate([row_roots, col_roots], axis=0)  # (4k, 90)
    data_root = nmt_ops.rfc6962_root_pow2(all_roots)  # (32,) replicated

    eds_local = jnp.concatenate([top[:, None], bot[:, None]], axis=1)
    # (k/R, 2, 2k, B): [:, 0] = top-half rows, [:, 1] = bottom-half rows
    return eds_local, row_roots, col_roots, data_root


@lru_cache(maxsize=None)
def _sharded_fn(mesh: Mesh, k: int, batched: bool, codec: str):
    R = mesh.shape["row"]
    if k % R:
        raise ValueError(f"square size {k} not divisible by row shards {R}")
    G = jnp.asarray(encode_matrix_bits(k, codec))
    body = partial(_sharded_extend_and_roots, G=G, k=k, n_row_shards=R)

    if not batched:
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=P("row", None, None),
            out_specs=(P("row", None, None, None), P(), P(), P()),
            check_rep=False,
        )
        return jax.jit(fn)

    vbody = jax.vmap(body)
    fn = shard_map(
        vbody,
        mesh=mesh,
        in_specs=P("data", "row", None, None),
        out_specs=(
            P("data", "row", None, None, None),
            P("data"),
            P("data"),
            P("data"),
        ),
        check_rep=False,
    )
    return jax.jit(fn)


def _reassemble_eds(eds_local: np.ndarray, k: int) -> np.ndarray:
    """(k, 2, 2k, B) row-shard layout -> (2k, 2k, B)."""
    top = eds_local[:, 0]  # (k, 2k, B)
    bot = eds_local[:, 1]
    return np.concatenate([top, bot], axis=0)


def extend_and_roots_sharded(square: np.ndarray, mesh: Mesh):
    """Sharded fused hot path on a mesh: square uint8[k,k,512] ->
    (eds uint8[2k,2k,512], row_roots, col_roots, data_root)."""
    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    sharding = NamedSharding(mesh, P("row", None, None))
    x = jax.device_put(jnp.asarray(square), sharding)
    eds_local, row_roots, col_roots, data_root = _sharded_fn(mesh, k, False, _active_codec())(x)
    eds = _reassemble_eds(np.asarray(eds_local), k)
    return eds, np.asarray(row_roots), np.asarray(col_roots), np.asarray(data_root)


def extend_and_roots_sharded_batch(squares: np.ndarray, mesh: Mesh):
    """Batched sharded path: uint8[n, k, k, 512], n divisible by the data
    axis -> (eds[n,2k,2k,512], row_roots[n,2k,90], col_roots[n,2k,90],
    data_roots[n,32])."""
    squares = np.asarray(squares, dtype=np.uint8)
    n, k = squares.shape[0], squares.shape[1]
    sharding = NamedSharding(mesh, P("data", "row", None, None))
    x = jax.device_put(jnp.asarray(squares), sharding)
    eds_local, row_roots, col_roots, data_roots = _sharded_fn(mesh, k, True, _active_codec())(x)
    eds_local = np.asarray(eds_local)
    eds = np.stack([_reassemble_eds(eds_local[i], k) for i in range(n)])
    return eds, np.asarray(row_roots), np.asarray(col_roots), np.asarray(data_roots)
