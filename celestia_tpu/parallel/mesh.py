"""Mesh provider: makes the multi-chip mesh the default device backend.

The sharded extension pipeline (parallel/sharded.py) has been proven
byte-identical to the single-device path since MULTICHIP_r01, but only
as a dryrun.  This module is the missing policy layer that decides, once
per process, whether the LIVE proposal lifecycle should dispatch through
it:

* **Discovery.**  At first use the provider inspects the jax backend.
  More than one accelerator device visible ⇒ the mesh is ON by default
  (the ROADMAP tentpole: "make the mesh the default device backend when
  >1 device is visible").  A CPU backend auto-resolves to OFF — the
  host regime's pooled native pipeline (da/dah.py) is the proven fast
  path there, and XLA's *forced* host devices
  (``--xla_force_host_platform_device_count``) are virtual slices of
  one physical CPU, so auto-sharding over them buys nothing.  Tests and
  smokes opt the virtual mesh in with an explicit spec.
* **Factoring.**  The mesh axes are ``data x row`` (multi-square batch
  x intra-square row sharding).  Auto picks ``1 x R`` with R the
  largest power of two ≤ the device count: the live path's dominant
  workload is ONE square per block, so all chips go to the row axis
  (rows of a power-of-two square always divide a power-of-two R ≤ k).
  Operators override with ``CELESTIA_TPU_MESH`` / ``--mesh`` —
  ``"2x4"`` (data x row), ``"auto"``, or ``"off"``.  An explicit
  factoring also forces the mesh ON over a CPU backend (how the tier-1
  mesh tests and `make multichip-smoke` engage the virtual 8-device
  mesh).
* **Per-square fallback.**  :func:`mesh_for_square` returns the mesh
  only when the square's rows divide the row axis (``k % R == 0`` and
  ``k >= R``); otherwise the caller falls back to the single-device
  path — tiny/empty squares (the min-DAH's k=1) never pay a mesh
  dispatch.  Fallbacks are counted (:func:`stats`).
* **Degradation ladder** (specs/robustness.md): a sharded dispatch
  failure mid-flight calls :func:`poison` — a one-way pin to the
  single-device path for the rest of the process (the same contract as
  utils/native.py's poison), loud in stats and telemetry, cleared only
  by ``clear_poison(force=True)`` (tests/operator).  A malformed mesh
  spec poisons at resolution instead of raising on the block hot path.

Layering (celint R8): parallel sits between da and state — state/app.py
imports this module (forward edge), da never does.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

ENV_MESH = "CELESTIA_TPU_MESH"

_OFF_SPECS = ("off", "none", "0", "false", "no", "single")
_AUTO_SPECS = ("", "auto", "on", "1", "true", "yes")

_lock = threading.Lock()
# serializes FIRST-USE resolution only (ordered before _lock): without
# it two racing first callers each build a distinct Mesh object, and
# since the sharded program cache keys on the Mesh instance, every
# program would compile twice and the threads would forever key
# different cache entries for identical programs
_resolve_lock = threading.Lock()
_configured: Optional[str] = None  # --mesh override; celint: guarded-by(_lock)
# resolved (mesh, data, row) or None, plus a "was resolved" flag so a
# None result is cached too; celint: guarded-by(_lock)
_resolved: Optional[Tuple[object, int, int]] = None
_resolved_done = False  # celint: guarded-by(_lock)
_poison_reason: Optional[str] = None  # celint: guarded-by(_lock)
_fallback_k: int = 0  # squares routed single-device (k % row != 0)
_sharded_extends: int = 0  # squares routed through the mesh
_batched_dispatches: int = 0  # batched multi-square dispatches


def parse_spec(spec: str) -> Optional[Tuple[int, int]]:
    """``"DxR"`` -> (data, row); ``"off"``-family -> (0, 0) sentinel;
    ``"auto"``-family -> None.  Raises ValueError on garbage."""
    s = str(spec).strip().lower()
    if s in _AUTO_SPECS:
        return None
    if s in _OFF_SPECS:
        return (0, 0)
    parts = s.split("x")
    if len(parts) != 2 or not all(p.isdigit() for p in parts):
        raise ValueError(
            f"mesh spec must be 'DATAxROW' (e.g. 2x4), 'auto' or 'off'; "
            f"got {spec!r}"
        )
    data, row = int(parts[0]), int(parts[1])
    if data < 1 or row < 1:
        raise ValueError(f"mesh axes must be >= 1, got {spec!r}")
    return (data, row)


def configure(spec: Optional[str]) -> None:
    """CLI override (``start --mesh``): validated eagerly (raises on a
    malformed spec — startup is the loud place), cached resolution is
    dropped so the next use re-resolves."""
    global _configured, _resolved, _resolved_done
    if spec is not None:
        parse_spec(spec)  # raise here, not on the block hot path
    with _lock:
        _configured = spec
        _resolved = None
        _resolved_done = False


def poison(reason: str) -> None:
    """One-way pin to the single-device path (first reason wins — the
    original fault must not be overwritten by knock-on failures)."""
    global _poison_reason
    from celestia_tpu.utils import faults

    with _lock:
        if _poison_reason is not None:
            return
        _poison_reason = reason
    faults.record_degradation("mesh", reason)


def poisoned() -> Optional[str]:
    with _lock:
        return _poison_reason


def clear_poison(force: bool = False) -> None:
    """Tests/operator intervention only: the pin is one-way by contract."""
    global _poison_reason, _resolved, _resolved_done
    if not force:
        raise RuntimeError(
            "mesh poison is a one-way degradation pin; pass force=True "
            "only from tests or deliberate operator intervention"
        )
    with _lock:
        _poison_reason = None
        _resolved = None
        _resolved_done = False


def _auto_factoring() -> Optional[Tuple[int, int]]:
    """Default policy: all devices on the row axis, none on data.
    None when the mesh should stay off (CPU backend / single device)."""
    import jax

    if jax.default_backend() == "cpu":
        return None
    n = int(jax.local_device_count())
    if n < 2:
        return None
    row = 1
    while row * 2 <= n:
        row *= 2
    return (1, row)


def _resolve():
    """Build (mesh, data, row) or None from spec/env/auto.  Runs with NO
    lock held (jax backend init can be slow); the caller caches the
    result under the module lock."""
    spec = _configured
    if spec is None:
        spec = os.environ.get(ENV_MESH, "")
    try:
        factoring = parse_spec(spec)
    except ValueError as e:
        poison(f"malformed mesh spec: {e}")
        return None
    explicit = factoring is not None and factoring != (0, 0)
    if factoring == (0, 0):
        return None
    if factoring is None:
        factoring = _auto_factoring()
    if factoring is None:
        return None
    data, row = factoring
    import jax

    # process-LOCAL devices, matching _auto_factoring's count: on a
    # multi-host backend each process meshes over its own chips —
    # jax.devices() would hand every host the global list and host 1
    # would device_put onto chips it does not own
    devices = jax.local_devices()
    if data * row > len(devices):
        poison(
            f"mesh spec {data}x{row} needs {data * row} devices, "
            f"{len(devices)} visible"
        )
        return None
    if not explicit and len(devices) < 2:
        return None
    from celestia_tpu.parallel.sharded import make_mesh

    mesh = make_mesh(devices[: data * row], data=data, row=row)
    return (mesh, data, row)


def device_mesh():
    """The process mesh, or None (single-device path).  Resolved once;
    ``configure``/``clear_poison(force=True)`` drop the cache."""
    global _resolved, _resolved_done
    with _lock:
        if _poison_reason is not None:
            return None
        if _resolved_done:
            return _resolved[0] if _resolved is not None else None
    with _resolve_lock:
        # double-check: the race loser reuses the winner's Mesh instead
        # of building (and later compiling against) its own
        with _lock:
            if _poison_reason is not None:
                return None
            if _resolved_done:
                return _resolved[0] if _resolved is not None else None
        try:
            resolved = _resolve()
        except Exception as e:  # backend init failure: degrade, never raise
            poison(f"mesh resolution failed: {e!r}")
            resolved = None
        with _lock:
            if _poison_reason is not None:
                return None
            _resolved = resolved
            _resolved_done = True
            return resolved[0] if resolved is not None else None


def mesh_shape() -> Optional[Tuple[int, int]]:
    """(data, row) of the active mesh, or None."""
    if device_mesh() is None:
        return None
    with _lock:
        return (_resolved[1], _resolved[2]) if _resolved is not None else None


def mesh_for_square(k: int, count_fallback: bool = True):
    """The mesh when square size ``k`` can shard over the row axis
    (``k % row == 0`` and ``k >= row``), else None — the per-square
    clean fallback to the single-device path.  ``count_fallback=False``
    keeps group-level probes (mesh_for_batch) out of the per-SQUARE
    fallback counter — each square in a fallen-back group is counted
    once, on its own routing."""
    global _fallback_k
    mesh = device_mesh()
    if mesh is None:
        return None
    row = int(mesh.shape["row"])
    if k < row or k % row:
        if count_fallback:
            with _lock:
                _fallback_k += 1
        return None
    return mesh


def mesh_for_batch(k: int, n: int):
    """The mesh when a batch of ``n`` same-k squares can run the batched
    leg: the square shards over ``row`` and the batch is non-empty (the
    batch is padded to a multiple of the ``data`` axis by the caller)."""
    if n < 1:
        return None
    return mesh_for_square(k, count_fallback=False)


def record_sharded_extend(batched: bool = False, squares: int = 1) -> None:
    """Bookkeeping from the sharded entries (parallel/sharded.py)."""
    global _sharded_extends, _batched_dispatches
    with _lock:
        _sharded_extends += squares
        if batched:
            _batched_dispatches += 1


def stats() -> dict:
    """Operational snapshot (status RPC / exposition / tests)."""
    with _lock:
        resolved = _resolved
        out = {
            "configured": _configured,
            "env": os.environ.get(ENV_MESH, ""),
            "resolved": _resolved_done,
            "active": resolved is not None and _poison_reason is None,
            "poisoned": _poison_reason,
            "fallback_squares": _fallback_k,
            "sharded_extends": _sharded_extends,
            "batched_dispatches": _batched_dispatches,
        }
        if resolved is not None:
            out["data"] = resolved[1]
            out["row"] = resolved[2]
        return out


def _reset_for_tests() -> None:
    """Drop ALL provider state (tests only — the provider is pin-once
    per process by design)."""
    global _configured, _resolved, _resolved_done, _poison_reason
    global _fallback_k, _sharded_extends, _batched_dispatches
    with _lock:
        _configured = None
        _resolved = None
        _resolved_done = False
        _poison_reason = None
        _fallback_k = 0
        _sharded_extends = 0
        _batched_dispatches = 0
