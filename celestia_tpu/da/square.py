"""Deterministic data-square construction (build for proposers, construct for
validators).

Behavioral parity with go-square's ``square.Build`` / ``square.Construct`` as
used at /root/reference/app/prepare_proposal.go:54 and
app/process_proposal.go:121, following the layout rules of
specs/src/specs/data_square_layout.md and ADR-020 (deterministic square
construction):

* shares ordered by namespace: TX ns < PFB ns < primary-reserved padding <
  user blobs (ns-sorted) < tail padding;
* blobs start at a multiple of their subtree width (non-interactive default
  rules, ADR-013), with namespace padding in the gaps;
* the square is the smallest power-of-two size that fits, capped by
  ``max_square_size``; Build drops overflowing txs, Construct errors.

The layout is square-size independent (subtree width depends only on blob
length), so placement indexes are stable across the fit search.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from celestia_tpu.appconsts import (
    DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    DEFAULT_SUBTREE_ROOT_THRESHOLD,
    SUPPORTED_SHARE_VERSIONS,
    round_up_power_of_two,
)
from celestia_tpu.da.blob import (
    Blob,
    BlobTx,
    IndexWrapper,
    unmarshal_blob_tx,
)
from celestia_tpu.da.namespace import (
    Namespace,
    PAY_FOR_BLOB_NAMESPACE,
    TRANSACTION_NAMESPACE,
)
from celestia_tpu.da.shares import (
    Share,
    namespace_padding_shares,
    parse_compact_shares,
    parse_sparse_shares,
    reserved_padding_shares,
    shares_to_array,
    split_blob_into_shares,
    split_txs_into_shares,
    tail_padding_shares,
)


def min_square_size(share_count: int) -> int:
    """Smallest power-of-two width whose square holds ``share_count`` shares."""
    if share_count <= 1:
        return 1
    ceil_sqrt = math.isqrt(share_count - 1) + 1
    return round_up_power_of_two(ceil_sqrt)


def subtree_width(share_count: int, threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD) -> int:
    """Width of the subtree-root mountains for a blob (ADR-013).

    min(RoundUpPowerOfTwo(ceil(n / threshold)), MinSquareSize(n)).
    """
    q = -(-share_count // threshold)
    return min(round_up_power_of_two(q), min_square_size(share_count))


def next_share_index(cursor: int, blob_share_len: int, threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD) -> int:
    """First aligned index >= cursor where a blob may start."""
    width = subtree_width(blob_share_len, threshold)
    return -(-cursor // width) * width


@dataclass(frozen=True)
class Square:
    """An original (unextended) data square of k*k shares, row-major."""

    shares: Tuple[Share, ...]
    size: int  # width k

    def __post_init__(self):
        if len(self.shares) != self.size * self.size:
            raise ValueError(
                f"square size {self.size} needs {self.size**2} shares, got {len(self.shares)}"
            )

    def to_array(self) -> np.ndarray:
        """uint8[k*k, 512] for the device extension pipeline."""
        return shares_to_array(self.shares)

    def is_empty(self) -> bool:
        return self.size == 1 and self.shares[0].namespace.is_padding()


@dataclass
class _PlacedBlob:
    blob: Blob
    order: int  # position in priority (input) order — stable sort key
    start: int = -1


def validate_blob_tx_layout(blob_tx: BlobTx) -> None:
    """Layout-level BlobTx validity: namespaces usable, versions supported,
    data non-empty.  The proposer drops violators; the validator rejects the
    proposal (x/blob/types/blob_tx.go ValidateBlobTx parity, layout subset)."""
    if not blob_tx.blobs:
        raise ValueError("blob tx carries no blobs")
    for b in blob_tx.blobs:
        b.namespace.validate_for_blob()
        if b.share_version not in SUPPORTED_SHARE_VERSIONS:
            raise ValueError(f"unsupported share version {b.share_version}")
        if len(b.data) == 0:
            raise ValueError("blob data must be non-empty")


@dataclass
class Builder:
    """Incremental square builder with fit checking.

    Mirrors go-square's Builder: txs and blob-txs are appended in priority
    order; ``export`` lays out the final square and returns the block tx list
    (normal txs raw, PFB txs wrapped as :class:`IndexWrapper`).

    ``fits`` is O(1) in the common case: exact running compact-share counts
    plus lower/upper bounds on blob placement (upper bound counts each blob's
    worst-case alignment gap of subtree_width-1); the exact O(n) layout only
    runs when the bounds disagree about fitting, and is memoized by revision
    for reuse in ``export``.
    """

    max_square_size: int = DEFAULT_SQUARE_SIZE_UPPER_BOUND
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD
    txs: List[bytes] = field(default_factory=list)
    pfb_txs: List[bytes] = field(default_factory=list)  # unwrapped PFB tx bytes
    pfb_blob_counts: List[int] = field(default_factory=list)
    blobs: List[_PlacedBlob] = field(default_factory=list)
    # kept original raw txs (normal raws + BlobTx envelopes) in append order —
    # this is the block tx list validators re-construct the square from
    block_txs: List[bytes] = field(default_factory=list)
    # running byte totals of the two compact sequences (varint-delimited units)
    _tx_seq_len: int = 0
    _pfb_seq_len: int = 0
    # blob share totals: exact sum and worst-case alignment waste
    _blob_shares: int = 0
    _blob_waste_bound: int = 0
    _revision: int = 0
    _layout_cache: Optional[Tuple[int, Tuple[int, List[_PlacedBlob], int, int]]] = None

    @staticmethod
    def _unit_len(tx_len: int) -> int:
        from celestia_tpu.da.shares import _varint

        return len(_varint(tx_len)) + tx_len

    @staticmethod
    def _compact_shares_for_len(seq_len: int) -> int:
        from celestia_tpu.appconsts import (
            CONTINUATION_COMPACT_SHARE_CONTENT_SIZE,
            FIRST_COMPACT_SHARE_CONTENT_SIZE,
        )

        if seq_len == 0:
            return 0
        if seq_len <= FIRST_COMPACT_SHARE_CONTENT_SIZE:
            return 1
        rem = seq_len - FIRST_COMPACT_SHARE_CONTENT_SIZE
        return 1 + -(-rem // CONTINUATION_COMPACT_SHARE_CONTENT_SIZE)

    def _layout(self) -> Tuple[int, List[_PlacedBlob], int, int]:
        """Exact layout: (total shares used, placed blobs, n_tx, n_pfb)."""
        if self._layout_cache is not None and self._layout_cache[0] == self._revision:
            return self._layout_cache[1]
        n_tx = self._compact_shares_for_len(self._tx_seq_len)
        n_pfb = self._compact_shares_for_len(self._pfb_seq_len)
        cursor = n_tx + n_pfb
        placed = sorted(self.blobs, key=lambda p: (p.blob.namespace.raw, p.order))
        out: List[_PlacedBlob] = []
        for p in placed:
            ln = p.blob.shares_needed()
            start = next_share_index(cursor, ln, self.subtree_root_threshold)
            out.append(_PlacedBlob(p.blob, p.order, start))
            cursor = start + ln
        result = (cursor, out, n_tx, n_pfb)
        self._layout_cache = (self._revision, result)
        return result

    def current_size(self) -> int:
        total, _, _, _ = self._layout()
        return min_square_size(max(total, 1))

    def fits(self) -> bool:
        max_shares = self.max_square_size * self.max_square_size
        reserved = self._compact_shares_for_len(
            self._tx_seq_len
        ) + self._compact_shares_for_len(self._pfb_seq_len)
        lower = reserved + self._blob_shares
        if lower > max_shares:
            return False
        upper = reserved + self._blob_shares + self._blob_waste_bound
        if upper <= max_shares:
            return True
        total, _, _, _ = self._layout()
        return total <= max_shares

    def append_tx(self, tx: bytes) -> bool:
        """Tentatively add a normal tx; False (and rollback) if it overflows."""
        self.txs.append(tx)
        self._tx_seq_len += self._unit_len(len(tx))
        self._revision += 1
        if not self.fits():
            self.txs.pop()
            self._tx_seq_len -= self._unit_len(len(tx))
            self._revision += 1
            return False
        self.block_txs.append(tx)
        return True

    def append_blob_tx(self, blob_tx: BlobTx, raw: Optional[bytes] = None) -> bool:
        """Tentatively add a BlobTx; False (and rollback) if it overflows.

        Raises ValueError on an invalid BlobTx (caller decides drop vs reject).
        ``raw`` is the marshalled envelope recorded in the block tx list
        (re-marshalled if omitted).
        """
        validate_blob_tx_layout(blob_tx)
        order0 = len(self.blobs)
        wrapper_len = IndexWrapper.marshalled_size(len(blob_tx.tx), len(blob_tx.blobs))
        d_pfb = self._unit_len(wrapper_len)
        d_shares = 0
        d_waste = 0
        for b in blob_tx.blobs:
            n = b.shares_needed()
            d_shares += n
            d_waste += subtree_width(n, self.subtree_root_threshold) - 1
        self.pfb_txs.append(blob_tx.tx)
        self.pfb_blob_counts.append(len(blob_tx.blobs))
        for b in blob_tx.blobs:
            self.blobs.append(_PlacedBlob(b, len(self.blobs)))
        self._pfb_seq_len += d_pfb
        self._blob_shares += d_shares
        self._blob_waste_bound += d_waste
        self._revision += 1
        if not self.fits():
            self.pfb_txs.pop()
            self.pfb_blob_counts.pop()
            del self.blobs[order0:]
            self._pfb_seq_len -= d_pfb
            self._blob_shares -= d_shares
            self._blob_waste_bound -= d_waste
            self._revision += 1
            return False
        self.block_txs.append(raw if raw is not None else blob_tx.marshal())
        return True

    def export(self) -> Tuple[Square, List[bytes], List[IndexWrapper]]:
        """Lay out the final square.

        Returns ``(square, block_txs, wrappers)``: the block tx list is the
        kept *original* raw txs (normal txs and BlobTx envelopes, priority
        order) — feeding it back through :func:`construct` reproduces the
        square byte-for-byte on the validator side; ``wrappers`` are the
        share-index-annotated PFB txs as written into the square's
        PAY_FOR_BLOB namespace (used at execution time).
        """
        total, placed, n_tx, n_pfb = self._layout()
        size = min_square_size(max(total, 1))
        if size > self.max_square_size:
            raise ValueError(
                f"square overflow: need size {size} > max {self.max_square_size}"
            )

        # Share indexes per PFB, in pfb_txs order.
        start_by_order = {p.order: p.start for p in placed}
        wrappers: List[IndexWrapper] = []
        order = 0
        for tx, n_blobs in zip(self.pfb_txs, self.pfb_blob_counts):
            idxs = tuple(start_by_order[order + i] for i in range(n_blobs))
            wrappers.append(IndexWrapper(tx, idxs))
            order += n_blobs

        shares: List[Share] = []
        if self.txs:
            shares.extend(split_txs_into_shares(TRANSACTION_NAMESPACE, self.txs))
        if wrappers:
            shares.extend(
                split_txs_into_shares(
                    PAY_FOR_BLOB_NAMESPACE, [w.marshal() for w in wrappers]
                )
            )
        assert len(shares) == n_tx + n_pfb, "compact share count drifted from layout"

        cursor = len(shares)
        prev_ns: Optional[Namespace] = None
        for p in placed:
            if p.start > cursor:
                pad_ns = prev_ns
                if pad_ns is None:
                    shares.extend(reserved_padding_shares(p.start - cursor))
                else:
                    shares.extend(namespace_padding_shares(pad_ns, p.start - cursor))
            blob_shares = split_blob_into_shares(
                p.blob.namespace, p.blob.data, p.blob.share_version
            )
            shares.extend(blob_shares)
            cursor = p.start + len(blob_shares)
            prev_ns = p.blob.namespace
        if len(shares) < size * size:
            shares.extend(tail_padding_shares(size * size - len(shares)))

        return Square(tuple(shares), size), list(self.block_txs), wrappers


def build(
    txs: Sequence[bytes],
    max_square_size: int = DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD,
) -> Tuple[Square, List[bytes], List[IndexWrapper]]:
    """Proposer path (app/prepare_proposal.go:54): lay out as many priority-
    ordered txs as fit; overflowing txs are dropped, never reordered."""
    b = Builder(max_square_size, subtree_root_threshold)
    for raw in txs:
        btx = unmarshal_blob_tx(raw)
        if btx is not None:
            try:
                b.append_blob_tx(btx, raw=raw)
            except ValueError:
                continue  # invalid BlobTx: proposer drops it
        else:
            b.append_tx(raw)
    return b.export()


def construct(
    txs: Sequence[bytes],
    max_square_size: int = DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD,
) -> Tuple[Square, List[bytes], List[IndexWrapper]]:
    """Validator path (app/process_proposal.go:121): re-lay out the proposed
    txs strictly; any overflow is an error (proposal rejected)."""
    b = Builder(max_square_size, subtree_root_threshold)
    for raw in txs:
        btx = unmarshal_blob_tx(raw)
        if btx is not None:
            ok = b.append_blob_tx(btx, raw=raw)
        else:
            ok = b.append_tx(raw)
        if not ok:
            raise ValueError("square construction overflow: proposal exceeds max square size")
    return b.export()


def extract_txs_and_blobs(
    square: Square,
) -> Tuple[List[bytes], List[bytes], List[Tuple[Namespace, bytes]]]:
    """Parse a square back into (normal txs, wrapped PFB txs, blobs)."""
    tx_shares = [s for s in square.shares if s.namespace.raw == TRANSACTION_NAMESPACE.raw]
    pfb_shares = [s for s in square.shares if s.namespace.raw == PAY_FOR_BLOB_NAMESPACE.raw]
    blob_shares = [
        s
        for s in square.shares
        if s.namespace.is_usable_by_users()
    ]
    txs = parse_compact_shares(tx_shares) if tx_shares else []
    pfbs = parse_compact_shares(pfb_shares) if pfb_shares else []
    blobs = parse_sparse_shares(blob_shares)
    return txs, pfbs, blobs
