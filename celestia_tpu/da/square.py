"""Deterministic data-square construction (build for proposers, construct for
validators).

Behavioral parity with go-square's ``square.Build`` / ``square.Construct`` as
used at /root/reference/app/prepare_proposal.go:54 and
app/process_proposal.go:121, following the layout rules of
specs/src/specs/data_square_layout.md and ADR-020 (deterministic square
construction):

* shares ordered by namespace: TX ns < PFB ns < primary-reserved padding <
  user blobs (ns-sorted) < tail padding;
* blobs start at a multiple of their subtree width (non-interactive default
  rules, ADR-013), with namespace padding in the gaps;
* the square is the smallest power-of-two size that fits, capped by
  ``max_square_size``; Build drops overflowing txs, Construct errors.

The layout is square-size independent (subtree width depends only on blob
length), so placement indexes are stable across the fit search.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from celestia_tpu.appconsts import (
    DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    DEFAULT_SUBTREE_ROOT_THRESHOLD,
    SUPPORTED_SHARE_VERSIONS,
    round_up_power_of_two,
)
from celestia_tpu.da.blob import (
    Blob,
    BlobTx,
    IndexWrapper,
    unmarshal_blob_tx,
)
from celestia_tpu.da.namespace import (
    Namespace,
    PAY_FOR_BLOB_NAMESPACE,
    PRIMARY_RESERVED_PADDING_NAMESPACE,
    TAIL_PADDING_NAMESPACE,
    TRANSACTION_NAMESPACE,
)
from celestia_tpu.da.shares import (
    SHARE_SIZE,
    Share,
    blob_shares_array,
    padding_share,
    parse_compact_shares,
    parse_sparse_shares,
    shares_to_array,
    split_txs_into_shares,
)


def min_square_size(share_count: int) -> int:
    """Smallest power-of-two width whose square holds ``share_count`` shares."""
    if share_count <= 1:
        return 1
    ceil_sqrt = math.isqrt(share_count - 1) + 1
    return round_up_power_of_two(ceil_sqrt)


def subtree_width(share_count: int, threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD) -> int:
    """Width of the subtree-root mountains for a blob (ADR-013).

    min(RoundUpPowerOfTwo(ceil(n / threshold)), MinSquareSize(n)).
    """
    q = -(-share_count // threshold)
    return min(round_up_power_of_two(q), min_square_size(share_count))


def next_share_index(cursor: int, blob_share_len: int, threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD) -> int:
    """First aligned index >= cursor where a blob may start."""
    width = subtree_width(blob_share_len, threshold)
    return -(-cursor // width) * width


class Square:
    """An original (unextended) data square of k*k shares, row-major.

    Backed by EITHER a Share tuple or a uint8[k*k, 512] array; the other
    representation materializes lazily.  The builder's export writes the
    array directly (one numpy pass), so the PrepareProposal hot path
    never creates the 16k Share objects a k=128 square would need — the
    object view exists for proofs, parsers and tests that want it.
    """

    __slots__ = ("size", "_shares", "_array")

    def __init__(
        self,
        shares: Optional[Sequence[Share]] = None,
        size: int = 0,
        array: Optional[np.ndarray] = None,
    ):
        if shares is None and array is None:
            raise ValueError("Square needs shares or an array")
        if shares is not None:
            shares = tuple(shares)
            if len(shares) != size * size:
                raise ValueError(
                    f"square size {size} needs {size**2} shares, "
                    f"got {len(shares)}"
                )
        if array is not None:
            array = np.ascontiguousarray(array, dtype=np.uint8)
            if array.shape != (size * size, 512):
                raise ValueError(
                    f"square size {size} needs uint8[{size**2}, 512], "
                    f"got {array.shape}"
                )
            # freeze OUR view only — ascontiguousarray may return the
            # caller's own object, whose flags are not ours to change
            array = array.view()
            array.flags.writeable = False  # shared view; see to_array
        self.size = size
        self._shares = shares
        self._array = array

    @property
    def shares(self) -> Tuple[Share, ...]:
        if self._shares is None:
            from celestia_tpu.da.shares import array_to_shares

            self._shares = tuple(array_to_shares(self._array))
        return self._shares

    def to_array(self) -> np.ndarray:
        """uint8[k*k, 512] for the device pipeline.  Read-only: the array
        is shared with the Square (copy before mutating)."""
        if self._array is None:
            arr = shares_to_array(self._shares)
            arr.flags.writeable = False
            self._array = arr
        return self._array

    def is_empty(self) -> bool:
        return self.size == 1 and self.shares[0].namespace.is_padding()


@dataclass
class _PlacedBlob:
    blob: Blob
    order: int  # position in priority (input) order — stable sort key
    start: int = -1


def validate_blob_tx_layout(blob_tx: BlobTx) -> None:
    """Layout-level BlobTx validity: namespaces usable, versions supported,
    data non-empty.  The proposer drops violators; the validator rejects the
    proposal (x/blob/types/blob_tx.go ValidateBlobTx parity, layout subset)."""
    if not blob_tx.blobs:
        raise ValueError("blob tx carries no blobs")
    for b in blob_tx.blobs:
        b.namespace.validate_for_blob()
        if b.share_version not in SUPPORTED_SHARE_VERSIONS:
            raise ValueError(f"unsupported share version {b.share_version}")
        if len(b.data) == 0:
            raise ValueError("blob data must be non-empty")


@dataclass
class Builder:
    """Incremental square builder with fit checking.

    Mirrors go-square's Builder: txs and blob-txs are appended in priority
    order; ``export`` lays out the final square and returns the block tx list
    (normal txs raw, PFB txs wrapped as :class:`IndexWrapper`).

    ``fits`` is O(1) in the common case: exact running compact-share counts
    plus lower/upper bounds on blob placement (upper bound counts each blob's
    worst-case alignment gap of subtree_width-1); the exact O(n) layout only
    runs when the bounds disagree about fitting, and is memoized by revision
    for reuse in ``export``.
    """

    max_square_size: int = DEFAULT_SQUARE_SIZE_UPPER_BOUND
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD
    txs: List[bytes] = field(default_factory=list)
    pfb_txs: List[bytes] = field(default_factory=list)  # unwrapped PFB tx bytes
    pfb_blob_counts: List[int] = field(default_factory=list)
    blobs: List[_PlacedBlob] = field(default_factory=list)
    # kept original raw txs (normal raws + BlobTx envelopes) in append order —
    # this is the block tx list validators re-construct the square from
    block_txs: List[bytes] = field(default_factory=list)
    # running byte totals of the two compact sequences (varint-delimited units)
    _tx_seq_len: int = 0
    _pfb_seq_len: int = 0
    # blob share totals: exact sum and worst-case alignment waste
    _blob_shares: int = 0
    _blob_waste_bound: int = 0
    _revision: int = 0
    _layout_cache: Optional[Tuple[int, Tuple[int, List[_PlacedBlob], int, int]]] = None

    @staticmethod
    def _unit_len(tx_len: int) -> int:
        from celestia_tpu.da.shares import _varint

        return len(_varint(tx_len)) + tx_len

    @staticmethod
    def _compact_shares_for_len(seq_len: int) -> int:
        from celestia_tpu.appconsts import (
            CONTINUATION_COMPACT_SHARE_CONTENT_SIZE,
            FIRST_COMPACT_SHARE_CONTENT_SIZE,
        )

        if seq_len == 0:
            return 0
        if seq_len <= FIRST_COMPACT_SHARE_CONTENT_SIZE:
            return 1
        rem = seq_len - FIRST_COMPACT_SHARE_CONTENT_SIZE
        return 1 + -(-rem // CONTINUATION_COMPACT_SHARE_CONTENT_SIZE)

    def _layout(self) -> Tuple[int, List[_PlacedBlob], int, int]:
        """Exact layout: (total shares used, placed blobs, n_tx, n_pfb)."""
        if self._layout_cache is not None and self._layout_cache[0] == self._revision:
            return self._layout_cache[1]
        n_tx = self._compact_shares_for_len(self._tx_seq_len)
        n_pfb = self._compact_shares_for_len(self._pfb_seq_len)
        cursor = n_tx + n_pfb
        placed = sorted(self.blobs, key=lambda p: (p.blob.namespace.raw, p.order))
        out: List[_PlacedBlob] = []
        for p in placed:
            ln = p.blob.shares_needed()
            start = next_share_index(cursor, ln, self.subtree_root_threshold)
            out.append(_PlacedBlob(p.blob, p.order, start))
            cursor = start + ln
        result = (cursor, out, n_tx, n_pfb)
        self._layout_cache = (self._revision, result)
        return result

    def current_size(self) -> int:
        total, _, _, _ = self._layout()
        return min_square_size(max(total, 1))

    def fits(self) -> bool:
        max_shares = self.max_square_size * self.max_square_size
        reserved = self._compact_shares_for_len(
            self._tx_seq_len
        ) + self._compact_shares_for_len(self._pfb_seq_len)
        lower = reserved + self._blob_shares
        if lower > max_shares:
            return False
        upper = reserved + self._blob_shares + self._blob_waste_bound
        if upper <= max_shares:
            return True
        total, _, _, _ = self._layout()
        return total <= max_shares

    def append_tx(self, tx: bytes) -> bool:
        """Tentatively add a normal tx; False (and rollback) if it overflows."""
        self.txs.append(tx)
        self._tx_seq_len += self._unit_len(len(tx))
        self._revision += 1
        if not self.fits():
            self.txs.pop()
            self._tx_seq_len -= self._unit_len(len(tx))
            self._revision += 1
            return False
        self.block_txs.append(tx)
        return True

    def append_blob_tx(self, blob_tx: BlobTx, raw: Optional[bytes] = None) -> bool:
        """Tentatively add a BlobTx; False (and rollback) if it overflows.

        Raises ValueError on an invalid BlobTx (caller decides drop vs reject).
        ``raw`` is the marshalled envelope recorded in the block tx list
        (re-marshalled if omitted).
        """
        validate_blob_tx_layout(blob_tx)
        order0 = len(self.blobs)
        wrapper_len = IndexWrapper.marshalled_size(len(blob_tx.tx), len(blob_tx.blobs))
        d_pfb = self._unit_len(wrapper_len)
        d_shares = 0
        d_waste = 0
        for b in blob_tx.blobs:
            n = b.shares_needed()
            d_shares += n
            d_waste += subtree_width(n, self.subtree_root_threshold) - 1
        self.pfb_txs.append(blob_tx.tx)
        self.pfb_blob_counts.append(len(blob_tx.blobs))
        for b in blob_tx.blobs:
            self.blobs.append(_PlacedBlob(b, len(self.blobs)))
        self._pfb_seq_len += d_pfb
        self._blob_shares += d_shares
        self._blob_waste_bound += d_waste
        self._revision += 1
        if not self.fits():
            self.pfb_txs.pop()
            self.pfb_blob_counts.pop()
            del self.blobs[order0:]
            self._pfb_seq_len -= d_pfb
            self._blob_shares -= d_shares
            self._blob_waste_bound -= d_waste
            self._revision += 1
            return False
        self.block_txs.append(raw if raw is not None else blob_tx.marshal())
        return True

    def export(self) -> Tuple[Square, List[bytes], List[IndexWrapper]]:
        """Lay out the final square.

        Returns ``(square, block_txs, wrappers)``: the block tx list is the
        kept *original* raw txs (normal txs and BlobTx envelopes, priority
        order) — feeding it back through :func:`construct` reproduces the
        square byte-for-byte on the validator side; ``wrappers`` are the
        share-index-annotated PFB txs as written into the square's
        PAY_FOR_BLOB namespace (used at execution time).
        """
        total, placed, n_tx, n_pfb = self._layout()
        size = min_square_size(max(total, 1))
        if size > self.max_square_size:
            raise ValueError(
                f"square overflow: need size {size} > max {self.max_square_size}"
            )

        # Share indexes per PFB, in pfb_txs order.
        start_by_order = {p.order: p.start for p in placed}
        wrappers: List[IndexWrapper] = []
        order = 0
        for tx, n_blobs in zip(self.pfb_txs, self.pfb_blob_counts):
            idxs = tuple(start_by_order[order + i] for i in range(n_blobs))
            wrappers.append(IndexWrapper(tx, idxs))
            order += n_blobs

        # One numpy pass straight into the square tensor: compact shares
        # (small count) via the Share path, blob sequences via the
        # vectorized splitter, padding by broadcast — no per-share Python
        # objects (16k of them at k=128 dominated the build phase).
        compact: List[Share] = []
        if self.txs:
            compact.extend(split_txs_into_shares(TRANSACTION_NAMESPACE, self.txs))
        if wrappers:
            compact.extend(
                split_txs_into_shares(
                    PAY_FOR_BLOB_NAMESPACE, [w.marshal() for w in wrappers]
                )
            )
        assert len(compact) == n_tx + n_pfb, "compact share count drifted from layout"

        arr = np.zeros((size * size, SHARE_SIZE), dtype=np.uint8)
        if compact:
            arr[: len(compact)] = np.frombuffer(
                b"".join(s.raw for s in compact), dtype=np.uint8
            ).reshape(len(compact), SHARE_SIZE)
        cursor = len(compact)
        prev_ns: Optional[Namespace] = None
        for p in placed:
            if p.start > cursor:
                pad_ns = (
                    prev_ns
                    if prev_ns is not None
                    else PRIMARY_RESERVED_PADDING_NAMESPACE
                )
                arr[cursor : p.start] = np.frombuffer(
                    padding_share(pad_ns).raw, dtype=np.uint8
                )
            blob_arr = blob_shares_array(
                p.blob.namespace, p.blob.data, p.blob.share_version
            )
            arr[p.start : p.start + blob_arr.shape[0]] = blob_arr
            cursor = p.start + blob_arr.shape[0]
            prev_ns = p.blob.namespace
        if cursor < size * size:
            arr[cursor:] = np.frombuffer(
                padding_share(TAIL_PADDING_NAMESPACE).raw, dtype=np.uint8
            )

        return Square(size=size, array=arr), list(self.block_txs), wrappers


def build(
    txs: Sequence[bytes],
    max_square_size: int = DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD,
) -> Tuple[Square, List[bytes], List[IndexWrapper]]:
    """Proposer path (app/prepare_proposal.go:54): lay out as many priority-
    ordered txs as fit; overflowing txs are dropped, never reordered."""
    b = Builder(max_square_size, subtree_root_threshold)
    for raw in txs:
        btx = unmarshal_blob_tx(raw)
        if btx is not None:
            try:
                b.append_blob_tx(btx, raw=raw)
            except ValueError:
                continue  # invalid BlobTx: proposer drops it
        else:
            b.append_tx(raw)
    return b.export()


def construct(
    txs: Sequence[bytes],
    max_square_size: int = DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD,
) -> Tuple[Square, List[bytes], List[IndexWrapper]]:
    """Validator path (app/process_proposal.go:121): re-lay out the proposed
    txs strictly; any overflow is an error (proposal rejected)."""
    b = Builder(max_square_size, subtree_root_threshold)
    for raw in txs:
        btx = unmarshal_blob_tx(raw)
        if btx is not None:
            ok = b.append_blob_tx(btx, raw=raw)
        else:
            ok = b.append_tx(raw)
        if not ok:
            raise ValueError("square construction overflow: proposal exceeds max square size")
    return b.export()


def extract_txs_and_blobs(
    square: Square,
) -> Tuple[List[bytes], List[bytes], List[Tuple[Namespace, bytes]]]:
    """Parse a square back into (normal txs, wrapped PFB txs, blobs)."""
    tx_shares = [s for s in square.shares if s.namespace.raw == TRANSACTION_NAMESPACE.raw]
    pfb_shares = [s for s in square.shares if s.namespace.raw == PAY_FOR_BLOB_NAMESPACE.raw]
    blob_shares = [
        s
        for s in square.shares
        if s.namespace.is_usable_by_users()
    ]
    txs = parse_compact_shares(tx_shares) if tx_shares else []
    pfbs = parse_compact_shares(pfb_shares) if pfb_shares else []
    blobs = parse_sparse_shares(blob_shares)
    return txs, pfbs, blobs
