"""Blob share commitments: merkle-mountain-range subtree roots (ADR-013).

Parity with go-square/inclusion.CreateCommitment as used by
/root/reference/x/blob/types/payforblob.go:49-56 (commitment creation) and
x/blob/types/blob_tx.go:98-107 (re-verification in ProcessProposal), and
with pkg/inclusion's commitment-from-EDS path conceptually: a blob's
commitment is the RFC-6962 merkle root over the NMT roots of its aligned
subtrees, whose widths form a merkle mountain range bounded by
SubtreeWidth(blob) — making the commitment independent of the square size
and equal to the subtree roots that appear in the row NMTs.

Subtree NMT roots are computed on the HOST (native C++ when available,
hashlib otherwise): a blob's mountains are tiny trees (<= 64 leaves), and
per-blob device dispatches would cost a round-trip + a shape-specific
compile each — hundreds of them per full-square proposal, dominating
PrepareProposal/ProcessProposal wall time.  The device keeps the big
batched work (the 4k axis trees); commitments are host work.
"""

from __future__ import annotations

from typing import List

import numpy as np

from celestia_tpu.appconsts import (
    DEFAULT_SUBTREE_ROOT_THRESHOLD,
    NAMESPACE_SIZE,
    round_down_power_of_two,
)
from celestia_tpu.da.blob import Blob
from celestia_tpu.da.square import subtree_width
from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.utils import native
from celestia_tpu.utils.lru import LruCache


def _commitment_weigher(key, value) -> int:
    """(sha256 digest, threshold) -> 32-byte commitment entries."""
    return len(key[0]) + len(value) + 64


def merkle_mountain_range_sizes(total: int, max_tree_size: int) -> List[int]:
    """Decompose ``total`` leaves into descending power-of-two mountains
    capped at ``max_tree_size``."""
    sizes: List[int] = []
    while total:
        if total >= max_tree_size:
            sizes.append(max_tree_size)
            total -= max_tree_size
        else:
            p = round_down_power_of_two(total)
            sizes.append(p)
            total -= p
    return sizes


def _nmt_root_host(leaves: np.ndarray) -> bytes:
    """Root of one small NMT on the host: native C++ or hashlib."""
    if native.available():
        return native.nmt_root(leaves).tobytes()
    level = [nmt_ops.leaf_digest_np(leaves[i].tobytes()) for i in range(len(leaves))]
    while len(level) > 1:
        level = [
            nmt_ops.combine_digests_np(level[2 * i], level[2 * i + 1])
            for i in range(len(level) // 2)
        ]
    return level[0]


# content-addressed commitment cache: the same blob's commitment is
# recomputed in CheckTx, FilterTxs AND ProcessProposal (the reference
# recomputes it at each of those validation points too); the digest key
# makes a hit deterministic and consensus-safe.  Shipped for two PRs as
# an UNLOCKED plain dict mutated from pooled threads (celint rule R1's
# founding true positive); now the unified thread-safe bounded LRU —
# every read/insert is atomic and the eviction loop is gone.
_COMMITMENT_CACHE = LruCache(
    "commitment", 8192, weigher=_commitment_weigher
)


def create_commitment(
    blob: Blob, subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD
) -> bytes:
    """32-byte share commitment of a blob."""
    key = _commitment_key(blob, subtree_root_threshold)
    cached = _COMMITMENT_CACHE.get(key)
    if cached is not None:
        return cached

    leaves, sizes = _blob_leaves(blob, subtree_root_threshold)
    if native.available():
        # one native call per blob (subtree roots + RFC-6962 fold inside)
        out = native.create_commitment(leaves, sizes)
    else:
        roots: List[bytes] = []
        offset = 0
        for s in sizes:
            roots.append(_nmt_root_host(leaves[offset : offset + s]))
            offset += s
        out = nmt_ops.rfc6962_root_np(roots).tobytes()
    # concurrent misses on one key race benignly: both compute the SAME
    # bytes (the commitment is a pure function of the key), last put wins
    _COMMITMENT_CACHE.put(key, out)
    return out


def create_commitments(blobs: List[Blob]) -> List[bytes]:
    return [create_commitment(b) for b in blobs]


def _commitment_key(blob: Blob, subtree_root_threshold: int):
    import hashlib

    return (
        hashlib.sha256(
            blob.namespace.raw + (blob.share_version & 0xFF).to_bytes(1, "big")
            + blob.data
        ).digest(),
        subtree_root_threshold,
    )


def _blob_leaves(blob: Blob, subtree_root_threshold: int):
    """(ns-prefixed NMT leaves uint8[n, 541], mountain sizes) for one
    blob — the single construction shared by create_commitment and
    warm_commitments (a consensus value must not have two layouts)."""
    from celestia_tpu.da.shares import blob_shares_array

    arr = blob_shares_array(blob.namespace, blob.data, blob.share_version)
    n = arr.shape[0]
    width = subtree_width(n, subtree_root_threshold)
    sizes = merkle_mountain_range_sizes(n, width)
    # NMT leaves: namespace-prefixed shares (Q0 rule — own namespace).
    ns = np.broadcast_to(
        np.frombuffer(blob.namespace.raw, dtype=np.uint8), (n, NAMESPACE_SIZE)
    )
    leaves = np.ascontiguousarray(np.concatenate([ns, arr], axis=1))
    return leaves, sizes


def warm_commitments(
    blobs: List[Blob],
    subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD,
) -> None:
    """Precompute commitments for MANY blobs in ONE native call and fill
    the cache, so the per-blob ``create_commitment`` calls inside tx
    validation all hit.  At proposal scale the per-blob ctypes crossing
    was a visible slice of FilterTxs (512 blobs x ~27 us call overhead);
    the batch shape also lets the C side thread across blobs.  No-op for
    blobs already cached; falls back to nothing (the per-blob path
    handles it) when the native library is absent.  Malformed blobs are
    skipped (best-effort): callers pass unvalidated envelopes, and the
    per-tx validate_blob_tx path reports them."""
    if not native.available():
        return

    pending: List[tuple] = []  # (key, leaves, sizes)
    seen = set()
    for blob in blobs:
        try:
            key = _commitment_key(blob, subtree_root_threshold)
            if key in seen or key in _COMMITMENT_CACHE:
                continue
            seen.add(key)
            leaves, sizes = _blob_leaves(blob, subtree_root_threshold)
        except (ValueError, OverflowError):
            # warming is best-effort over UNVALIDATED blobs: a malformed
            # one (empty data, bad share version) is simply skipped here
            # and reported properly by the per-tx validate_blob_tx path
            continue
        pending.append((key, leaves, sizes))
    if not pending:
        return
    leaves_all = np.ascontiguousarray(
        np.concatenate([p[1] for p in pending], axis=0)
    )
    blob_off = np.cumsum([0] + [p[1].shape[0] for p in pending])
    sizes_all = np.concatenate([p[2] for p in pending]).astype(np.int32)
    size_off = np.cumsum([0] + [len(p[2]) for p in pending])
    out = native.create_commitments_batch(
        leaves_all, blob_off, sizes_all, size_off
    )
    for i, (key, _, _) in enumerate(pending):
        _COMMITMENT_CACHE.put(key, out[i].tobytes())
