"""Blob share commitments: merkle-mountain-range subtree roots (ADR-013).

Parity with go-square/inclusion.CreateCommitment as used by
/root/reference/x/blob/types/payforblob.go:49-56 (commitment creation) and
x/blob/types/blob_tx.go:98-107 (re-verification in ProcessProposal), and
with pkg/inclusion's commitment-from-EDS path conceptually: a blob's
commitment is the RFC-6962 merkle root over the NMT roots of its aligned
subtrees, whose widths form a merkle mountain range bounded by
SubtreeWidth(blob) — making the commitment independent of the square size
and equal to the subtree roots that appear in the row NMTs.

Subtree NMT roots are computed on device, batched by mountain width.
"""

from __future__ import annotations

from typing import List

import numpy as np
import jax
import jax.numpy as jnp

from celestia_tpu.appconsts import (
    DEFAULT_SUBTREE_ROOT_THRESHOLD,
    NAMESPACE_SIZE,
    round_down_power_of_two,
)
from celestia_tpu.da.blob import Blob
from celestia_tpu.da.shares import shares_to_array, split_blob_into_shares
from celestia_tpu.da.square import subtree_width
from celestia_tpu.ops import nmt as nmt_ops


def merkle_mountain_range_sizes(total: int, max_tree_size: int) -> List[int]:
    """Decompose ``total`` leaves into descending power-of-two mountains
    capped at ``max_tree_size``."""
    sizes: List[int] = []
    while total:
        if total >= max_tree_size:
            sizes.append(max_tree_size)
            total -= max_tree_size
        else:
            p = round_down_power_of_two(total)
            sizes.append(p)
            total -= p
    return sizes


@jax.jit
def _subtree_roots(leaves: jnp.ndarray) -> jnp.ndarray:
    """uint8[n_trees, width, 541] -> uint8[n_trees, 90]."""
    return nmt_ops.nmt_roots(leaves)


def create_commitment(
    blob: Blob, subtree_root_threshold: int = DEFAULT_SUBTREE_ROOT_THRESHOLD
) -> bytes:
    """32-byte share commitment of a blob."""
    shares = split_blob_into_shares(blob.namespace, blob.data, blob.share_version)
    arr = shares_to_array(shares)  # (n, 512)
    n = arr.shape[0]
    width = subtree_width(n, subtree_root_threshold)
    sizes = merkle_mountain_range_sizes(n, width)
    # NMT leaves: namespace-prefixed shares (Q0 rule — own namespace).
    ns = np.broadcast_to(
        np.frombuffer(blob.namespace.raw, dtype=np.uint8), (n, NAMESPACE_SIZE)
    )
    leaves = np.concatenate([ns, arr], axis=1)  # (n, 541)
    # batch subtree roots by mountain size
    roots: List[bytes] = [b""] * len(sizes)
    offset = 0
    offsets = []
    for s in sizes:
        offsets.append(offset)
        offset += s
    by_size = {}
    for i, s in enumerate(sizes):
        by_size.setdefault(s, []).append(i)
    for s, idxs in by_size.items():
        batch = np.stack([leaves[offsets[i] : offsets[i] + s] for i in idxs])
        out = np.asarray(_subtree_roots(jnp.asarray(batch)))
        for j, i in enumerate(idxs):
            roots[i] = out[j].tobytes()
    return nmt_ops.rfc6962_root_np(roots).tobytes()


def create_commitments(blobs: List[Blob]) -> List[bytes]:
    return [create_commitment(b) for b in blobs]
