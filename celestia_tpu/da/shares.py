"""512-byte share encoding/decoding: sparse (blob), compact (tx), padding.

Behavioral parity with go-square/shares as specified in
/root/reference/specs/src/specs/shares.md and the layout constants in
/root/reference/pkg/appconsts/global_consts.go:29-66.

Shares are the atomic unit of the data square.  Layout of every share:

    [29B namespace][1B info (7-bit version | 1-bit sequence-start)]
    [4B big-endian sequence length — first share of a sequence only]
    [4B big-endian reserved bytes   — compact (tx) shares only]
    [payload, zero-filled]

On the host, shares are plain ``bytes``; :func:`shares_to_array` exports a
square as a ``uint8[n, 512]`` numpy array for the device pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, List, Sequence, Tuple

import numpy as np

from celestia_tpu.appconsts import (
    COMPACT_SHARE_RESERVED_BYTES,
    CONTINUATION_COMPACT_SHARE_CONTENT_SIZE,
    CONTINUATION_SPARSE_SHARE_CONTENT_SIZE,
    FIRST_COMPACT_SHARE_CONTENT_SIZE,
    FIRST_SPARSE_SHARE_CONTENT_SIZE,
    DEFAULT_SHARE_VERSION,
    MAX_SHARE_VERSION,
    NAMESPACE_SIZE,
    SEQUENCE_LEN_BYTES,
    SHARE_INFO_BYTES,
    SHARE_SIZE,
    SUPPORTED_SHARE_VERSIONS,
)
from celestia_tpu.da.namespace import (
    Namespace,
    PRIMARY_RESERVED_PADDING_NAMESPACE,
    TAIL_PADDING_NAMESPACE,
)


@dataclass(frozen=True)
class Share:
    """One 512-byte share."""

    raw: bytes

    def __post_init__(self):
        if len(self.raw) != SHARE_SIZE:
            raise ValueError(f"share must be {SHARE_SIZE} bytes, got {len(self.raw)}")

    @property
    def namespace(self) -> Namespace:
        return Namespace(self.raw[:NAMESPACE_SIZE])

    @property
    def info_byte(self) -> int:
        return self.raw[NAMESPACE_SIZE]

    @property
    def version(self) -> int:
        return self.info_byte >> 1

    @property
    def is_sequence_start(self) -> bool:
        return bool(self.info_byte & 1)

    def sequence_len(self) -> int:
        """Big-endian uint32 sequence length (sequence-start shares only)."""
        if not self.is_sequence_start:
            raise ValueError("sequence length only present on sequence-start shares")
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        return int.from_bytes(self.raw[off : off + SEQUENCE_LEN_BYTES], "big")

    def is_compact(self) -> bool:
        from celestia_tpu.da.namespace import (
            PAY_FOR_BLOB_NAMESPACE,
            TRANSACTION_NAMESPACE,
        )

        return self.namespace.raw in (
            TRANSACTION_NAMESPACE.raw,
            PAY_FOR_BLOB_NAMESPACE.raw,
        )

    def reserved_bytes(self) -> int:
        """Compact shares: absolute in-share index of the first unit start (0 = none)."""
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        if self.is_sequence_start:
            off += SEQUENCE_LEN_BYTES
        return int.from_bytes(self.raw[off : off + COMPACT_SHARE_RESERVED_BYTES], "big")

    def sparse_payload(self) -> bytes:
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        if self.is_sequence_start:
            off += SEQUENCE_LEN_BYTES
        return self.raw[off:]

    def compact_payload(self) -> bytes:
        off = NAMESPACE_SIZE + SHARE_INFO_BYTES
        if self.is_sequence_start:
            off += SEQUENCE_LEN_BYTES
        off += COMPACT_SHARE_RESERVED_BYTES
        return self.raw[off:]


def _info_byte(version: int, sequence_start: bool) -> int:
    if not 0 <= version <= MAX_SHARE_VERSION:
        raise ValueError(f"share version out of range: {version}")
    return (version << 1) | int(sequence_start)


# ---------------------------------------------------------------------------
# Sparse (blob) shares
# ---------------------------------------------------------------------------


def split_blob_into_shares(
    namespace: Namespace, data: bytes, share_version: int = DEFAULT_SHARE_VERSION
) -> List[Share]:
    """Split one blob into its share sequence (specs/shares.md "Share Splitting")."""
    # Padding shares are the only zero-length sequences; blobs must be
    # non-empty (x/blob MsgPayForBlobs validation in the reference) —
    # blob_shares_array enforces both that and the share version.
    # Vectorized layout (one numpy pass instead of per-share bytes
    # concatenation: the square-build hot path at k=128 lays out ~16k
    # shares), wrapped back into Share objects for the layout machinery.
    arr = blob_shares_array(namespace, data, share_version)
    flat = arr.tobytes()
    return [
        Share(flat[i * SHARE_SIZE : (i + 1) * SHARE_SIZE])
        for i in range(arr.shape[0])
    ]


def sparse_shares_needed(blob_len: int) -> int:
    """Number of shares a blob of ``blob_len`` bytes occupies."""
    if blob_len <= FIRST_SPARSE_SHARE_CONTENT_SIZE:
        return 1
    rem = blob_len - FIRST_SPARSE_SHARE_CONTENT_SIZE
    return 1 + -(-rem // CONTINUATION_SPARSE_SHARE_CONTENT_SIZE)


def blob_shares_array(
    namespace: Namespace, data: bytes, share_version: int = DEFAULT_SHARE_VERSION
) -> "np.ndarray":
    """Vectorized split_blob_into_shares: uint8[n, 512] directly, no Share
    objects.  Bit-identical to the per-share path (asserted in tests); used
    where only the tensor is needed (commitment recompute runs once per
    blob per proposal — the Python share loop dominated that host cost)."""
    import numpy as np

    if share_version not in SUPPORTED_SHARE_VERSIONS:
        raise ValueError(f"unsupported share version {share_version}")
    if len(data) == 0:
        raise ValueError("blob data must be non-empty")
    n = sparse_shares_needed(len(data))
    arr = np.zeros((n, SHARE_SIZE), dtype=np.uint8)
    ns = np.frombuffer(namespace.raw, dtype=np.uint8)
    arr[:, :NAMESPACE_SIZE] = ns
    info_off = NAMESPACE_SIZE
    arr[0, info_off] = _info_byte(share_version, True)
    if n > 1:
        arr[1:, info_off] = _info_byte(share_version, False)
    seq_off = info_off + SHARE_INFO_BYTES
    arr[0, seq_off : seq_off + SEQUENCE_LEN_BYTES] = np.frombuffer(
        len(data).to_bytes(SEQUENCE_LEN_BYTES, "big"), dtype=np.uint8
    )
    first_off = seq_off + SEQUENCE_LEN_BYTES
    buf = np.frombuffer(data, dtype=np.uint8)
    first_n = min(len(data), FIRST_SPARSE_SHARE_CONTENT_SIZE)
    arr[0, first_off : first_off + first_n] = buf[:first_n]
    rest = buf[first_n:]
    if rest.size:
        cont_off = info_off + SHARE_INFO_BYTES
        padded = np.zeros(
            (n - 1) * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE, dtype=np.uint8
        )
        padded[: rest.size] = rest
        arr[1:, cont_off : cont_off + CONTINUATION_SPARSE_SHARE_CONTENT_SIZE] = (
            padded.reshape(n - 1, CONTINUATION_SPARSE_SHARE_CONTENT_SIZE)
        )
    return arr


def parse_sparse_shares(shares: Sequence[Share]) -> List[Tuple[Namespace, bytes]]:
    """Reassemble (namespace, blob-bytes) sequences from sparse shares.

    Padding sequences (sequence length 0) are skipped.
    """
    blobs: List[Tuple[Namespace, bytes]] = []
    i = 0
    while i < len(shares):
        sh = shares[i]
        if not sh.is_sequence_start:
            raise ValueError(f"share {i}: expected sequence start")
        seq_len = sh.sequence_len()
        if seq_len == 0:  # padding share
            i += 1
            continue
        ns = sh.namespace
        data = bytearray(sh.sparse_payload())
        i += 1
        while len(data) < seq_len:
            if i >= len(shares):
                raise ValueError("truncated share sequence")
            cont = shares[i]
            if cont.is_sequence_start or cont.namespace.raw != ns.raw:
                raise ValueError(f"share {i}: broken sequence continuation")
            data.extend(cont.sparse_payload())
            i += 1
        blobs.append((ns, bytes(data[:seq_len])))
    return blobs


# ---------------------------------------------------------------------------
# Compact (transaction) shares
# ---------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    """Unsigned LEB128 varint (protobuf-style), as used for tx unit delimiters.

    Values are bounded to uint64 — symmetric with :func:`_read_varint`.
    """
    if n < 0 or n >= 1 << 64:
        raise ValueError(f"varint value out of uint64 range: {n}")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            # minimal-encoding rule (specs/wire.md "Primitives"): a
            # multi-byte varint must not end in a zero group — without
            # this, the same value has many encodings and a signed tx's
            # wire bytes become malleable (sign_bytes covers the
            # verbatim wire slices, SignDoc parity)
            if b == 0 and shift > 0:
                raise ValueError("non-minimal varint encoding")
            if result >= 1 << 64:
                raise ValueError("varint exceeds uint64 range")
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def split_txs_into_shares(namespace: Namespace, txs: Sequence[bytes]) -> List[Share]:
    """Write length-delimited txs into one compact share sequence.

    Reserved bytes hold the absolute in-share index of the first unit that
    starts in that share (0 if none) — specs/shares.md "Transaction Shares".
    """
    units = b"".join(_varint(len(tx)) + tx for tx in txs)
    seq_len = len(units)
    if seq_len == 0:
        return []  # consistent with compact_shares_needed([]) == 0

    # Content capacity per share.
    caps = [FIRST_COMPACT_SHARE_CONTENT_SIZE]
    n_shares = 1
    total = caps[0]
    while total < seq_len:
        caps.append(CONTINUATION_COMPACT_SHARE_CONTENT_SIZE)
        total += CONTINUATION_COMPACT_SHARE_CONTENT_SIZE
        n_shares += 1

    # Absolute content offsets where each unit starts.
    unit_starts = []
    pos = 0
    for tx in txs:
        unit_starts.append(pos)
        pos += len(_varint(len(tx))) + len(tx)

    shares: List[Share] = []
    content_pos = 0
    unit_idx = 0
    for share_i in range(n_shares):
        cap = caps[share_i]
        chunk = units[content_pos : content_pos + cap]
        # First unit starting within [content_pos, content_pos + len(chunk))
        reserved = 0
        while unit_idx < len(unit_starts) and unit_starts[unit_idx] < content_pos:
            unit_idx += 1
        if unit_idx < len(unit_starts) and unit_starts[unit_idx] < content_pos + cap:
            in_share_off = unit_starts[unit_idx] - content_pos
            header = NAMESPACE_SIZE + SHARE_INFO_BYTES + COMPACT_SHARE_RESERVED_BYTES
            if share_i == 0:
                header += SEQUENCE_LEN_BYTES
            reserved = header + in_share_off
        if share_i == 0:
            raw = (
                namespace.raw
                + bytes([_info_byte(DEFAULT_SHARE_VERSION, True)])
                + seq_len.to_bytes(SEQUENCE_LEN_BYTES, "big")
                + reserved.to_bytes(COMPACT_SHARE_RESERVED_BYTES, "big")
                + chunk
            )
        else:
            raw = (
                namespace.raw
                + bytes([_info_byte(DEFAULT_SHARE_VERSION, False)])
                + reserved.to_bytes(COMPACT_SHARE_RESERVED_BYTES, "big")
                + chunk
            )
        shares.append(Share(raw.ljust(SHARE_SIZE, b"\x00")))
        content_pos += cap
    return shares


def parse_compact_shares(shares: Sequence[Share]) -> List[bytes]:
    """Reassemble the length-delimited tx list from a compact share sequence.

    Strict: one sequence, uniform namespace, zero padding beyond the sequence
    length — a malformed square must fail here, not decode loosely.
    """
    if not shares:
        return []
    if not shares[0].is_sequence_start:
        raise ValueError("compact sequence must begin with a sequence-start share")
    ns_raw = shares[0].namespace.raw
    seq_len = shares[0].sequence_len()
    content = bytearray()
    for i, sh in enumerate(shares):
        if i > 0 and sh.is_sequence_start:
            raise ValueError(f"compact share {i}: unexpected second sequence start")
        if sh.namespace.raw != ns_raw:
            raise ValueError(f"compact share {i}: namespace mismatch")
        content.extend(sh.compact_payload())
    if len(content) < seq_len:
        raise ValueError("compact sequence shorter than declared sequence length")
    if any(content[seq_len:]):
        raise ValueError("nonzero padding after compact sequence content")
    content = bytes(content[:seq_len])
    txs: List[bytes] = []
    pos = 0
    while pos < len(content):
        tx_len, pos = _read_varint(content, pos)
        if pos + tx_len > len(content):
            raise ValueError("truncated tx unit")
        txs.append(content[pos : pos + tx_len])
        pos += tx_len
    return txs


def compact_shares_needed(txs: Sequence[bytes]) -> int:
    seq_len = sum(len(_varint(len(t))) + len(t) for t in txs)
    if seq_len == 0:
        return 0
    if seq_len <= FIRST_COMPACT_SHARE_CONTENT_SIZE:
        return 1
    rem = seq_len - FIRST_COMPACT_SHARE_CONTENT_SIZE
    return 1 + -(-rem // CONTINUATION_COMPACT_SHARE_CONTENT_SIZE)


# ---------------------------------------------------------------------------
# Padding shares
# ---------------------------------------------------------------------------


def padding_share(namespace: Namespace) -> Share:
    """A padding share: sequence start, sequence length 0, zero payload."""
    raw = (
        namespace.raw
        + bytes([_info_byte(DEFAULT_SHARE_VERSION, True)])
        + (0).to_bytes(SEQUENCE_LEN_BYTES, "big")
    )
    return Share(raw.ljust(SHARE_SIZE, b"\x00"))


def namespace_padding_shares(namespace: Namespace, n: int) -> List[Share]:
    return [padding_share(namespace) for _ in range(n)]


def reserved_padding_shares(n: int) -> List[Share]:
    return [padding_share(PRIMARY_RESERVED_PADDING_NAMESPACE) for _ in range(n)]


def tail_padding_shares(n: int) -> List[Share]:
    return [padding_share(TAIL_PADDING_NAMESPACE) for _ in range(n)]


# ---------------------------------------------------------------------------
# Device export
# ---------------------------------------------------------------------------


def shares_to_array(shares: Iterable[Share]) -> np.ndarray:
    """Pack shares into a ``uint8[n, 512]`` array for the device pipeline.
    One join + one frombuffer instead of a copy per share (16k shares at
    k=128 made the per-share loop a measurable slice of PrepareProposal)."""
    joined = b"".join(sh.raw for sh in shares)
    out = np.frombuffer(joined, dtype=np.uint8).reshape(-1, SHARE_SIZE)
    return out.copy()  # callers may mutate; frombuffer views are read-only


def array_to_shares(arr: np.ndarray) -> List[Share]:
    if arr.ndim != 2 or arr.shape[1] != SHARE_SIZE or arr.dtype != np.uint8:
        raise ValueError(f"expected uint8[n, {SHARE_SIZE}], got {arr.dtype}{arr.shape}")
    return [Share(arr[i].tobytes()) for i in range(arr.shape[0])]
