"""Content-addressed EDS/DAH cache for the proposal lifecycle.

The north-star workload runs ExtendBlock TWICE per block per validator:
the proposer extends its own square in PrepareProposal and then
re-extends the identical square when it ProcessProposal-validates its
own block; every other validator re-extends the same square once per
gossip validation, and round restarts re-extend it again.  The square —
and therefore the EDS and DAH — is a pure function of

    (block txs, square size, app version, active share codec)

so those repeats are content-addressed lookups, not recomputes ("On the
Encoding Process in Decentralized Systems", arxiv 2408.15203: redundant
re-encoding of unchanged data dominates decentralized encoding cost).

Safety invariants (enforced here and pinned by tests/test_eds_cache.py):

* The key is a sha256 over the FULL length-prefixed tx bytes plus the
  layout/version/codec parameters — NEVER the claimed data_root.  A
  byzantine proposer that advertises the data_root of a cached honest
  block but ships different txs hashes to a different key, recomputes,
  and is rejected on the root mismatch like before.
* Only the extend is ever skipped.  ProcessProposal's ante checks,
  signature verification and strict square reconstruction still run on
  every proposal; the cache replaces only `extend_block(square)`, whose
  input the caller has already re-derived from the tx bytes.
* Entries are immutable pairs (ExtendedDataSquare, DataAvailabilityHeader)
  inserted only after an honest local computation.  A hit returns the
  exact object a cold run would have produced byte-for-byte (asserted
  for both codecs by the tests).

The cache is process-global (one chain per process — the same pin-once
invariant the codec selection documents in ops/gf256.py) and bounded:
a 128x128 EDS is ~32 MiB of shares, so the LRU holds a handful of
recent proposals, which covers the prepare->process->commit lifecycle
of the current height plus round-restart re-proposals.
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

from celestia_tpu.utils.lru import LruCache, nbytes_weigher

_KEY_DOMAIN = b"celestia-tpu/eds-cache/v1|"

# ~8 entries x ~32 MiB (k=128 host EDS) keeps the worst case around a
# quarter GiB; smaller squares are proportionally cheaper.  Overridable
# for memory-constrained deployments.
DEFAULT_MAX_ENTRIES = int(os.environ.get("CELESTIA_TPU_EDS_CACHE", "8"))


def make_key(
    block_txs: List[bytes], square_size: int, app_version: int, codec: str
) -> bytes:
    """sha256(canonical block_txs || square_size || app_version || codec).

    Txs are length-prefixed so shifting bytes across tx boundaries can
    never alias two different proposals to one key; the claimed
    data_root is deliberately NOT part of the key (see module docs).
    """
    h = hashlib.sha256()
    h.update(_KEY_DOMAIN)
    h.update(len(block_txs).to_bytes(4, "big"))
    for raw in block_txs:
        h.update(len(raw).to_bytes(4, "big"))
        h.update(raw)
    h.update(int(square_size).to_bytes(4, "big"))
    h.update(int(app_version).to_bytes(8, "big"))
    h.update(codec.encode())
    return h.digest()


def min_dah_key(codec: str) -> bytes:
    """Key of the minimal (empty) square's entry — the first resident of
    the cache (da/dah.py min_data_availability_header).  Identical to a
    genuine empty proposal's key modulo the app_version sentinel: the
    value is the same either way (build([]) IS the empty block's square),
    but the min-DAH is version-independent so it pins version 0."""
    return make_key([], 1, 0, codec)


class EdsCache:
    """Bounded, thread-safe LRU of content-key -> (eds, dah).

    Thin domain wrapper over the unified :class:`LruCache` — the pair
    API (``put(key, eds, dah)``), the legacy stats keys and the min-DAH
    ``peek`` semantics are preserved byte-for-byte for existing callers
    (bench.py, tests/test_eds_cache.py)."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self._lru = LruCache(
            "eds", max_entries, weigher=nbytes_weigher
        )

    @property
    def max_entries(self) -> int:
        return self._lru.max_entries

    def get(self, key: bytes) -> Optional[Tuple[object, object]]:
        return self._lru.get(key)

    def peek(self, key: bytes) -> Optional[Tuple[object, object]]:
        """get() without touching the hit/miss counters (the min-DAH
        lookups would drown the block-level hit rate).  LRU recency IS
        refreshed: the min-DAH entry must not sit perpetually first in
        the eviction line just because its reads never count."""
        return self._lru.peek(key)

    def put(self, key: bytes, eds, dah) -> None:
        self._lru.put(key, (eds, dah))

    def clear(self) -> None:
        self._lru.clear()

    def __len__(self) -> int:
        return len(self._lru)

    def stats(self) -> dict:
        s = self._lru.stats()
        # legacy stat surface (pinned by tests + BENCH history): puts
        # counts every insert, including replacements
        return {
            "entries": s["entries"],
            "hits": s["hits"],
            "misses": s["misses"],
            "puts": s["puts"] + s["replacements"],
            "evictions": s["evictions"],
            "hit_rate": s["hit_rate"],
            "approx_bytes": s["approx_bytes"],
        }


# The process-global instance every App / dah helper shares (content-
# addressed keys make sharing across App instances in one process safe:
# two apps that hash to the same key would compute the same bytes).
CACHE = EdsCache()


def get(key: bytes):
    return CACHE.get(key)


def put(key: bytes, eds, dah) -> None:
    CACHE.put(key, eds, dah)


def clear() -> None:
    CACHE.clear()
    _DEVICE_CACHE.clear()


def stats() -> dict:
    return CACHE.stats()


# ---------------------------------------------------------------------------
# Device-buffer handle companion cache (da/device_plane.py)
# ---------------------------------------------------------------------------
# Beside each content-addressed (eds, dah) pair, the device-resident
# plane parks a DevicePlaneEntry — the SAME block's EDS, NMT level
# stacks and root-tree levels still on their chip — keyed by data_root,
# which is what process/commit and DAS serving hold when they come
# looking.  Keying by data_root is safe here precisely because it is
# NOT safe above: entries are inserted only after an honest local
# computation produced that root, and a miss (eviction, byzantine
# root, device loss) degrades to the byte-identical host path — never
# to trusting a claimed root.
#
# The byte budget is explicit and conservative: a k=128 entry weighs
# ~56 MiB of HBM (32 MiB shares + ~24 MiB digest levels), so the
# defaults hold the prepare->process->commit lifecycle of the current
# height plus one re-proposal.  Entry weights come from array shapes
# (DevicePlaneEntry.nbytes) — weighing never forces a transfer.

DEFAULT_DEVICE_ENTRIES = int(os.environ.get("CELESTIA_TPU_EDS_DEVICE", "4"))
DEFAULT_DEVICE_MB = int(os.environ.get("CELESTIA_TPU_EDS_DEVICE_MB", "256"))

_DEVICE_CACHE = LruCache(
    "eds_device",
    DEFAULT_DEVICE_ENTRIES,
    weigher=lambda _key, entry: int(getattr(entry, "nbytes", 0)),
    max_bytes=DEFAULT_DEVICE_MB * (1 << 20),
)


def put_device_entry(data_root: bytes, entry) -> None:
    """Park a DevicePlaneEntry for ``data_root`` (evicts LRU handles
    beyond the entry/byte budget; the dropped blocks become plain host-
    path misses)."""
    _DEVICE_CACHE.put(bytes(data_root), entry)


def get_device_entry(data_root: bytes):
    """The device-warm handle for ``data_root``, or None (evicted /
    never proposed here / plane disabled) — None means host fallback."""
    return _DEVICE_CACHE.get(bytes(data_root))


_DROP_MISS = object()


def drop_device_entry(data_root: bytes) -> bool:
    """Evict one handle (device-loss handling, tests).  True if it was
    resident."""
    return _DEVICE_CACHE.pop(bytes(data_root), _DROP_MISS) is not _DROP_MISS


def device_handle_stats() -> dict:
    return _DEVICE_CACHE.stats()
