"""Bad-encoding fraud proofs (BEFP): disprove a maliciously-encoded square.

Role: the fraud-proof half of the availability story (reference spec
`specs/src/specs/fraud_proofs.md`): if a proposer commits DAH roots over a
square that is NOT a Reed-Solomon codeword, any full node that notices can
produce a compact proof that convinces a light client to reject the header
— k shares of the broken axis, each proven against the ORTHOGONAL axis's
committed root, whose RS completion hashes to a different root than the
one committed for the broken axis.

Soundness: the k shares are pinned by NMT proofs to roots inside the same
DAH the light client already holds, and RS decoding from ANY k points of a
codeword reproduces the codeword — so if the recomputed axis root differs
from the committed one, the committed axis cannot be a codeword, no matter
which k positions the prover picked.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from celestia_tpu.appconsts import NAMESPACE_SIZE, SHARE_SIZE
from celestia_tpu.da.dah import DataAvailabilityHeader
from celestia_tpu.da.das import _host_level_stack
from celestia_tpu.da.namespace import PARITY_SHARE_NAMESPACE
from celestia_tpu.da.proof import NmtRangeProof, nmt_range_proof_from_levels
from celestia_tpu.ops import gf256

_PARITY_NS = PARITY_SHARE_NAMESPACE.raw

AXIS_ROW = "row"
AXIS_COL = "col"


def _cell_prefix(row: int, col: int, k: int, share: bytes) -> bytes:
    """Q0 cells keep their own namespace; parity cells get the parity
    namespace (the wrapper Push rule both axis trees share)."""
    if row < k and col < k:
        return share[:NAMESPACE_SIZE]
    return _PARITY_NS


def _axis_leaves(cells: np.ndarray, axis: str, index: int, k: int) -> np.ndarray:
    """NMT leaves of one full axis given its 2k cells."""
    n = 2 * k
    out = np.empty((n, NAMESPACE_SIZE + SHARE_SIZE), dtype=np.uint8)
    for j in range(n):
        r, c = (index, j) if axis == AXIS_ROW else (j, index)
        share = cells[j].tobytes()
        out[j, :NAMESPACE_SIZE] = np.frombuffer(
            _cell_prefix(r, c, k, share), dtype=np.uint8
        )
        out[j, NAMESPACE_SIZE:] = cells[j]
    return out


def _axis_root(cells: np.ndarray, axis: str, index: int, k: int) -> bytes:
    levels = _host_level_stack(_axis_leaves(cells, axis, index, k))
    return levels[-1][0].tobytes()


@dataclass(frozen=True)
class BadEncodingProof:
    """Proof that the committed axis `index` is not an RS codeword."""

    axis: str  # AXIS_ROW / AXIS_COL
    index: int
    square_size: int  # original k
    positions: Tuple[int, ...]  # k distinct positions along the axis
    shares: Tuple[bytes, ...]  # the committed cells at those positions
    # share i proven at leaf `index` of the ORTHOGONAL tree positions[i]
    proofs: Tuple[NmtRangeProof, ...]

    def verify(self, dah: DataAvailabilityHeader) -> bool:
        """True iff the fraud is PROVEN against this DAH (a True result
        means the header must be rejected)."""
        k = self.square_size
        n = 2 * k
        if self.axis not in (AXIS_ROW, AXIS_COL):
            return False
        if not 0 <= self.index < n:
            return False
        if len(dah.row_roots) != n or len(dah.col_roots) != n:
            return False
        if len(self.positions) != k or len(set(self.positions)) != k:
            return False
        if len(self.shares) != k or len(self.proofs) != k:
            return False
        if any(len(s) != SHARE_SIZE for s in self.shares):
            return False
        orth_roots = (
            dah.col_roots if self.axis == AXIS_ROW else dah.row_roots
        )
        for pos, share, proof in zip(self.positions, self.shares, self.proofs):
            if not 0 <= pos < n:
                return False
            # cell (index, pos) for a row sits at leaf `index` of column
            # pos's tree (and symmetrically for columns)
            if proof.start != self.index or proof.end != self.index + 1:
                return False
            r, c = (
                (self.index, pos) if self.axis == AXIS_ROW else (pos, self.index)
            )
            leaf = _cell_prefix(r, c, k, share) + share
            if not proof.verify(orth_roots[pos], [leaf], n):
                return False
        # reconstruct the full axis from the k proven cells
        D = gf256.decode_matrices_batch(
            np.asarray([self.positions], dtype=np.uint8), k
        )[0]  # (2k, k)
        X = np.frombuffer(b"".join(self.shares), dtype=np.uint8).reshape(
            k, SHARE_SIZE
        )
        full = gf256.gf_matmul(D, X)
        committed_root = (
            dah.row_roots[self.index]
            if self.axis == AXIS_ROW
            else dah.col_roots[self.index]
        )
        recomputed = _axis_root(full, self.axis, self.index, k)
        return recomputed != committed_root

    def to_dict(self) -> dict:
        return {
            "axis": self.axis,
            "index": self.index,
            "square_size": self.square_size,
            "positions": list(self.positions),
            "shares": [s.hex() for s in self.shares],
            "proofs": [
                {"start": p.start, "end": p.end,
                 "nodes": [x.hex() for x in p.nodes]}
                for p in self.proofs
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BadEncodingProof":
        return cls(
            axis=d["axis"],
            index=int(d["index"]),
            square_size=int(d["square_size"]),
            positions=tuple(int(p) for p in d["positions"]),
            shares=tuple(bytes.fromhex(s) for s in d["shares"]),
            proofs=tuple(
                NmtRangeProof(
                    int(p["start"]), int(p["end"]),
                    tuple(bytes.fromhex(x) for x in p["nodes"]),
                )
                for p in d["proofs"]
            ),
        )


def detect_bad_encoding(
    eds_shares: np.ndarray,
) -> Optional[Tuple[str, int]]:
    """Full-node detection: find an axis whose committed cells are not an
    RS codeword (reconstructing from its first k cells disagrees with the
    rest).  Returns (axis, index) or None for an honestly-encoded square.

    Operates on the shares alone — codeword-ness is a property of the
    square; the DAH only enters when a BEFP is VERIFIED against it."""
    eds_shares = np.asarray(eds_shares, dtype=np.uint8)
    n = eds_shares.shape[0]
    k = n // 2
    D = gf256.decode_matrices_batch(
        np.arange(k, dtype=np.uint8)[None, :], k
    )[0]
    for axis in (AXIS_ROW, AXIS_COL):
        data = eds_shares if axis == AXIS_ROW else eds_shares.transpose(1, 0, 2)
        for idx in range(n):
            full = gf256.gf_matmul(D, data[idx, :k])
            if not np.array_equal(full, data[idx]):
                return axis, idx
    return None


def build_befp(
    eds_shares: np.ndarray,
    axis: str,
    index: int,
    positions: Optional[Tuple[int, ...]] = None,
) -> BadEncodingProof:
    """Prover: package k cells of the broken axis with proofs computed
    from the square itself (they bind to whatever DAH committed these
    shares; verification supplies that DAH)."""
    eds_shares = np.asarray(eds_shares, dtype=np.uint8)
    n = eds_shares.shape[0]
    k = n // 2
    if positions is None:
        positions = tuple(range(k))
    shares: List[bytes] = []
    proofs: List[NmtRangeProof] = []
    for pos in positions:
        r, c = (index, pos) if axis == AXIS_ROW else (pos, index)
        share = eds_shares[r, c].tobytes()
        # build the orthogonal tree (column pos for a row, row pos for a
        # column) and prove leaf `index` in it
        orth_axis = AXIS_COL if axis == AXIS_ROW else AXIS_ROW
        orth_cells = (
            eds_shares[:, pos] if orth_axis == AXIS_COL else eds_shares[pos]
        )
        levels = _host_level_stack(
            _axis_leaves(orth_cells, orth_axis, pos, k)
        )
        proofs.append(nmt_range_proof_from_levels(levels, index, index + 1))
        shares.append(share)
    return BadEncodingProof(
        axis=axis,
        index=index,
        square_size=k,
        positions=tuple(positions),
        shares=tuple(shares),
        proofs=tuple(proofs),
    )
