"""Golden vectors pinned from the reference Go stack, shared by the test
suite (tests/test_reference_vectors.py) and the bench device-path gate
(bench.py) so the pinned bytes and the fixture-share construction can
never silently diverge between the two.

Sources (all in /root/reference):
- pkg/da/data_availability_header_test.go:29  MinDataAvailabilityHeader hash
- pkg/da/data_availability_header_test.go:45  2x2 "typical" DAH hash
- pkg/da/data_availability_header_test.go:51  128x128 "max square size" DAH hash

Share fixture construction mirrors generateShares/generateShare
(data_availability_header_test.go:247-263): every share is the version-0
namespace 0x00 || 18*0x00 || 10*0x01 followed by 0xFF to ShareSize.
"""

import numpy as np

from celestia_tpu.appconsts import SHARE_SIZE
from celestia_tpu.da.namespace import Namespace

# pkg/da/data_availability_header_test.go:29
MIN_DAH_HASH = bytes.fromhex(
    "3d96b7d238e7e0456f6af8e7cdf0a67bd6cf9c2089ecb559c659dcaa1f880353"
)
# pkg/da/data_availability_header_test.go:45 ("typical", squareSize=2)
DAH_2X2_HASH = bytes.fromhex(
    "b56e4d251ac266f4b91cc5464b3fc7efcbdc888064647496d13133f0dc65ac25"
)
# pkg/da/data_availability_header_test.go:51 ("max square size", 128)
DAH_128_HASH = bytes.fromhex(
    "0bd3abeeacfbb0b92dfbdac4a154868e3c4e79666f7fcf6c620bb90dd3a0dcf0"
)


def fixture_share() -> bytes:
    """generateShare(ns1) parity: ns1 = MustNewV0(10 x 0x01), remainder
    0xFF to ShareSize."""
    ns1 = Namespace.v0(b"\x01" * 10)
    share = ns1.raw + b"\xff" * (SHARE_SIZE - len(ns1.raw))
    assert len(share) == SHARE_SIZE
    return share


def fixture_shares(count: int) -> np.ndarray:
    share = fixture_share()
    return np.frombuffer(share * count, dtype=np.uint8).reshape(
        count, SHARE_SIZE
    )
