"""Namespaces: 29-byte (1-byte version + 28-byte ID) share labels.

Behavioral parity with go-square/namespace as used by the reference
(/root/reference/specs/src/specs/namespace.md, pkg/appconsts/global_consts.go:17-27).
Namespaces order the data square and drive the Namespaced Merkle Tree; the
reserved primary namespaces hold transactions, the reserved secondary
namespaces hold padding and erasure parity.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_tpu.appconsts import (
    NAMESPACE_ID_SIZE,
    NAMESPACE_SIZE,
    NAMESPACE_VERSION_MAX,
    NAMESPACE_VERSION_SIZE,
    PARITY_SHARE_NAMESPACE_RAW,
)

# Version-0 namespaces must have 18 leading zero bytes in the 28-byte ID,
# leaving 10 user-specifiable bytes (specs/namespace.md "Version 0").
NAMESPACE_VERSION_ZERO = 0
NAMESPACE_VERSION_ZERO_PREFIX_LEN = 18
NAMESPACE_VERSION_ZERO_USER_LEN = NAMESPACE_ID_SIZE - NAMESPACE_VERSION_ZERO_PREFIX_LEN


@dataclass(frozen=True, order=True)
class Namespace:
    """An immutable 29-byte namespace; ordering is bytewise over version||id."""

    raw: bytes  # NAMESPACE_SIZE bytes: version || id

    def __post_init__(self):
        if len(self.raw) != NAMESPACE_SIZE:
            raise ValueError(
                f"namespace must be {NAMESPACE_SIZE} bytes, got {len(self.raw)}"
            )

    @property
    def version(self) -> int:
        return self.raw[0]

    @property
    def id(self) -> bytes:
        return self.raw[NAMESPACE_VERSION_SIZE:]

    @classmethod
    def from_version_id(cls, version: int, id_: bytes) -> "Namespace":
        if not 0 <= version <= NAMESPACE_VERSION_MAX:
            raise ValueError(f"invalid namespace version {version}")
        if len(id_) != NAMESPACE_ID_SIZE:
            raise ValueError(
                f"namespace id must be {NAMESPACE_ID_SIZE} bytes, got {len(id_)}"
            )
        return cls(bytes([version]) + id_)

    @classmethod
    def v0(cls, user_bytes: bytes) -> "Namespace":
        """Build a version-0 namespace from <=10 user bytes (left-padded)."""
        if len(user_bytes) > NAMESPACE_VERSION_ZERO_USER_LEN:
            raise ValueError(
                f"version-0 user namespace must be <= {NAMESPACE_VERSION_ZERO_USER_LEN}"
                f" bytes, got {len(user_bytes)}"
            )
        id_ = b"\x00" * (NAMESPACE_ID_SIZE - len(user_bytes)) + user_bytes
        return cls.from_version_id(NAMESPACE_VERSION_ZERO, id_)

    def is_reserved(self) -> bool:
        return self.is_primary_reserved() or self.is_secondary_reserved()

    def is_primary_reserved(self) -> bool:
        """<= 0x00..FF: version 0 and id <= 27 zero bytes + 0xFF."""
        return self.raw <= MAX_PRIMARY_RESERVED_NAMESPACE.raw

    def is_secondary_reserved(self) -> bool:
        """>= 0xFF..00: version 255 and 27 leading 0xFF id bytes."""
        return self.raw >= MIN_SECONDARY_RESERVED_NAMESPACE.raw

    def is_usable_by_users(self) -> bool:
        return not self.is_reserved()

    def validate_for_blob(self) -> None:
        """Blob namespaces must be version 0, non-reserved, with the v0 zero prefix."""
        if self.version != NAMESPACE_VERSION_ZERO:
            raise ValueError(f"blob namespace version must be 0, got {self.version}")
        if self.id[:NAMESPACE_VERSION_ZERO_PREFIX_LEN] != b"\x00" * NAMESPACE_VERSION_ZERO_PREFIX_LEN:
            raise ValueError("version-0 namespace id must have 18 leading zero bytes")
        if self.is_reserved():
            raise ValueError(f"namespace {self.raw.hex()} is reserved for protocol use")

    def is_parity(self) -> bool:
        return self.raw == PARITY_SHARE_NAMESPACE.raw

    def is_padding(self) -> bool:
        return self.raw in (
            TAIL_PADDING_NAMESPACE.raw,
            PRIMARY_RESERVED_PADDING_NAMESPACE.raw,
        )

    def hex(self) -> str:
        return self.raw.hex()

    def __repr__(self) -> str:
        return f"Namespace(0x{self.raw.hex()})"


def _primary(last_byte: int) -> Namespace:
    return Namespace(b"\x00" * (NAMESPACE_SIZE - 1) + bytes([last_byte]))


def _secondary(last_byte: int) -> Namespace:
    return Namespace(b"\xff" * (NAMESPACE_SIZE - 1) + bytes([last_byte]))


# Reserved namespaces (specs/namespace.md "Reserved Namespaces").
TRANSACTION_NAMESPACE = _primary(0x01)
INTERMEDIATE_STATE_ROOT_NAMESPACE = _primary(0x02)
PAY_FOR_BLOB_NAMESPACE = _primary(0x04)
PRIMARY_RESERVED_PADDING_NAMESPACE = _primary(0xFF)
MAX_PRIMARY_RESERVED_NAMESPACE = _primary(0xFF)
MIN_SECONDARY_RESERVED_NAMESPACE = _secondary(0x00)
TAIL_PADDING_NAMESPACE = _secondary(0xFE)
# the raw bytes are pinned in appconsts (ops/nmt.py consumes them below
# the da/ layer); wrapping them here keeps one source of truth
PARITY_SHARE_NAMESPACE = Namespace(PARITY_SHARE_NAMESPACE_RAW)
