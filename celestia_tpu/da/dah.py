"""Extended data square + DataAvailabilityHeader: the block-extension hot path.

Behavioral parity with /root/reference/pkg/da/data_availability_header.go
(ExtendShares :65-75, NewDataAvailabilityHeader :44-63, Hash :92-108,
ValidateBasic :134-177, MinDataAvailabilityHeader :179) and
app/extend_block.go:14-32 — redesigned as one fused, jit-compiled device
program: RS-extend (ops/rs.py bit-matmuls) -> all 4k NMT axis roots
(ops/nmt.py level-synchronous reduction) -> RFC-6962 data root, in a single
XLA executable per square size.  This runs twice per block per validator
(PrepareProposal / ProcessProposal) and is the BASELINE.json north star.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import lru_cache
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from celestia_tpu.appconsts import (
    DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    SHARE_SIZE,
    is_power_of_two,
)
from celestia_tpu.da.square import Square
from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.ops import rs
from celestia_tpu.ops.gf256 import active_codec as _active_codec
from celestia_tpu.ops.gf256 import encode_matrix_bits
from celestia_tpu.utils import tracing
from celestia_tpu.utils.lru import LruCache

NMT_ROOT_SIZE = nmt_ops.NMT_DIGEST_SIZE  # 90
DATA_ROOT_SIZE = 32


class ExtendedDataSquare:
    """A 2k x 2k erasure-extended share square (rsmt2d.ExtendedDataSquare parity).

    Holds the share tensor uint8[2k, 2k, 512]; Q0 (top-left k x k) is the
    original data square.
    """

    def __init__(self, shares):
        # Accepts a host array OR a device (jax) array.  A device-resident
        # EDS stays on the device until something actually reads the share
        # bytes (proof generation, gossip): PrepareProposal/ProcessProposal
        # only consume the roots, so the ~8-33 MiB device->host transfer
        # drops out of the block hot path (SURVEY §7 hard part c).
        if isinstance(shares, np.ndarray) or not hasattr(shares, "shape"):
            # host-coercible input (ndarray, list, tuple, ...)
            shares = np.asarray(shares, dtype=np.uint8)
        elif shares.dtype != np.uint8:  # device array with wrong dtype
            raise ValueError(f"EDS shares must be uint8, got {shares.dtype}")
        n = shares.shape[0]
        if shares.shape != (n, n, SHARE_SIZE) or n % 2 or not is_power_of_two(n // 2):
            raise ValueError(f"invalid EDS shape {shares.shape}")
        self._shares = shares

    @property
    def shares(self) -> np.ndarray:
        if not isinstance(self._shares, np.ndarray):
            self._shares = np.asarray(self._shares).astype(np.uint8, copy=False)
        return self._shares

    @property
    def width(self) -> int:
        # shape is metadata — never forces a device->host transfer
        return self._shares.shape[0]

    @property
    def square_size(self) -> int:
        """Original (unextended) square width k."""
        return self.width // 2

    def row(self, r: int) -> np.ndarray:
        return self.shares[r]

    def col(self, c: int) -> np.ndarray:
        return self.shares[:, c]

    def quadrant(self, q: int) -> np.ndarray:
        k = self.square_size
        r, c = divmod(q, 2)
        return self.shares[r * k : (r + 1) * k, c * k : (c + 1) * k]

    def flattened_original(self) -> np.ndarray:
        """Q0 as uint8[k*k, 512] (row-major original shares)."""
        k = self.square_size
        return self.quadrant(0).reshape(k * k, SHARE_SIZE)


@lru_cache(maxsize=None)
def _extend_and_roots_fn(k: int, codec: str):
    """Jitted fused pipeline for square size k:
    square uint8[k,k,512] -> (eds, row_roots[2k,90], col_roots[2k,90], data_root[32])."""
    G = jnp.asarray(encode_matrix_bits(k, codec))

    def run(square: jnp.ndarray):
        eds = rs._extend(square, G)
        roots = nmt_ops.eds_nmt_roots(eds)  # (2, 2k, 90)
        all_roots = roots.reshape(4 * k, NMT_ROOT_SIZE)
        data_root = nmt_ops.rfc6962_root_pow2(all_roots)
        return eds, roots[0], roots[1], data_root

    return jax.jit(run)


@dataclass(frozen=True)
class DataAvailabilityHeader:
    """Row/column NMT roots + memoized hash (= the block's data root)."""

    row_roots: Tuple[bytes, ...]
    col_roots: Tuple[bytes, ...]
    _hash: bytes

    @property
    def hash(self) -> bytes:
        return self._hash

    @property
    def square_size(self) -> int:
        return len(self.row_roots) // 2

    def validate_basic(self) -> None:
        """dah ValidateBasic parity: extended square bounds + root shapes +
        hash consistency (data_availability_header.go:134-177)."""
        n = len(self.row_roots)
        if n == 0 or n != len(self.col_roots):
            raise ValueError("row/col root counts must match and be non-empty")
        k = n // 2
        if n % 2 or not is_power_of_two(k):
            raise ValueError(f"extended square width {n} must be 2 * power-of-two")
        if k > DEFAULT_SQUARE_SIZE_UPPER_BOUND:
            raise ValueError(
                f"square size {k} exceeds upper bound {DEFAULT_SQUARE_SIZE_UPPER_BOUND}"
            )
        for r in (*self.row_roots, *self.col_roots):
            if len(r) != NMT_ROOT_SIZE:
                raise ValueError(f"NMT root must be {NMT_ROOT_SIZE} bytes")
        if self.compute_hash(self.row_roots, self.col_roots) != self._hash:
            raise ValueError("DAH hash does not match its roots")

    @staticmethod
    def compute_hash(row_roots, col_roots) -> bytes:
        return nmt_ops.rfc6962_root_np(list(row_roots) + list(col_roots)).tobytes()

    def to_bytes(self) -> bytes:
        """Deterministic wire form: counts + concatenated roots."""
        out = bytearray()
        out += len(self.row_roots).to_bytes(4, "big")
        for r in self.row_roots:
            out += r
        out += len(self.col_roots).to_bytes(4, "big")
        for c in self.col_roots:
            out += c
        return bytes(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DataAvailabilityHeader":
        n_rows = int.from_bytes(raw[:4], "big")
        pos = 4
        rows = []
        for _ in range(n_rows):
            rows.append(raw[pos : pos + NMT_ROOT_SIZE])
            pos += NMT_ROOT_SIZE
        n_cols = int.from_bytes(raw[pos : pos + 4], "big")
        pos += 4
        cols = []
        for _ in range(n_cols):
            cols.append(raw[pos : pos + NMT_ROOT_SIZE])
            pos += NMT_ROOT_SIZE
        if pos != len(raw):
            raise ValueError("trailing bytes in DAH encoding")
        dah = cls(tuple(rows), tuple(cols), cls.compute_hash(rows, cols))
        dah.validate_basic()
        return dah


def extend_shares(shares: np.ndarray) -> ExtendedDataSquare:
    """da.ExtendShares parity: uint8[n, 512] (n a perfect power-of-4 count)
    -> ExtendedDataSquare."""
    shares = np.asarray(shares, dtype=np.uint8)
    n = shares.shape[0]
    k = int(round(n**0.5))
    if k * k != n or not is_power_of_two(k):
        raise ValueError(f"share count {n} must be a square of a power of two")
    square = shares.reshape(k, k, SHARE_SIZE)
    eds = np.asarray(rs.extend_square(square))
    return ExtendedDataSquare(eds)


def _host_native_available() -> bool:
    """True when the host-regime fast path applies: the default backend
    is the CPU (device tunnel down / host-only deployment) and the
    native pooled pipeline is present."""
    from celestia_tpu.utils import native
    from celestia_tpu.utils.device import host_regime

    return host_regime() and native.available()


def _extend_and_header_host(
    square: np.ndarray,
) -> Tuple[ExtendedDataSquare, "DataAvailabilityHeader"]:
    """Host-regime ExtendBlock: the pooled native C++ pipeline with the
    extend->roots overlap (byte-identical to the device program — pinned
    by tests/test_leopard_codec.py / test_golden_vectors.py)."""
    from celestia_tpu.ops import gf256
    from celestia_tpu.utils import hostpool, native

    codec = gf256.active_codec()
    # the fused C++ call computes extension AND all 4k roots in its
    # 3-phase overlapped pipeline (row extend -> columns interleaved
    # with top-row roots -> remaining roots); the span args record the
    # fusion so the trace reader knows the roots phase below is the
    # Python-side DAH assembly, not the hashing itself.  Args (incl. the
    # cpu_threads() lock+env read) are built only when the tracer is on
    # — this is the per-block host hot path.
    span = (
        tracing.span(
            "extend.native",
            codec=codec,
            fused_roots=True,
            nthreads=hostpool.cpu_threads(),
            phases=3,
        )
        if tracing.enabled()
        else tracing.NULL_SPAN
    )
    with span:
        if codec == gf256.CODEC_LEOPARD:
            eds, roots, data_root = native.extend_block_leopard_cpu(square)
        else:
            eds, roots, data_root = native.extend_block_cpu(square)
    n2 = 2 * square.shape[0]
    with tracing.span("roots", stage="assemble", fused_native=True):
        dah = DataAvailabilityHeader(
            tuple(roots[i].tobytes() for i in range(n2)),
            tuple(roots[n2 + i].tobytes() for i in range(n2)),
            data_root.tobytes(),
        )
    return ExtendedDataSquare(eds), dah


# ---------------------------------------------------------------------------
# Row-level extension memoization (host regime).
#
# Consecutive heights share rows whose bytes have not changed — tail-padding
# rows, namespace-padding rows, unchanged blob rows — and within one square
# the padding rows are all identical.  Extension and the ROW tree are pure
# per-row functions: parity row r depends only on row r's bytes, and the
# NMT prefix rule (own ns for c < k, parity ns for c >= k) is the same for
# every original row index, so digest(row bytes) fully determines both the
# parity row and the extended row's NMT root.  Column extension and column
# roots depend on the whole square and always recompute.
#
# The memo serves the HOST regime legs only (native fused pipeline + the
# jax-on-CPU fallback).  The device leg deliberately bypasses it: a partial
# hit cannot shrink the fused XLA program, populating parity would force a
# ~32 MiB device->host fetch onto the hot path, and the device regime's
# redundant work is already eliminated one level up by the content-addressed
# EDS cache (da/eds_cache.py).
#
# MEASURED scoping (k=128, 2-core host, this PR): the leopard-native fused
# pipeline (FFT + overlapped extend->roots in C++) finishes in ~191 ms,
# while Python-orchestrated selective reuse costs ~250 ms even with 100%
# of rows memoized — the column FFT + full native roots + assembly copies
# alone exceed the fused total.  The memo therefore engages only where it
# measurably wins: the table-method (lagrange) native pipeline (~3.9 s
# fused at k=128 -> ~3x faster with 75% row reuse) and the no-native
# pure-Python fallback (proportional savings on every skipped row).  For
# leopard+native the memo is fully disabled — not even digests are
# computed — so the default host hot path carries zero overhead.
# ---------------------------------------------------------------------------


class _RowMemo:
    """(k, codec, sha256(row bytes)) -> (parity row bytes, row root bytes).

    Domain wrapper over the unified utils/lru.py cache; the batch API and
    the legacy stats keys (lookups/inserted/reuse_pct) are preserved for
    bench.py's BENCH_r0x series."""

    def __init__(self, max_entries: int):
        self._lru = LruCache(
            "row_memo", max_entries, weigher=_row_memo_weigher
        )
        # assembled is memo-path bookkeeping, not a cache counter; int
        # += is atomic enough for a stats field under CPython
        self.assembled = 0  # squares served by the memoized assembly path

    @property
    def max_entries(self) -> int:
        return self._lru.max_entries

    def lookup_many(self, k: int, codec: str, digests: List[bytes]):
        return self._lru.get_many((k, codec, d) for d in digests)

    def insert_many(self, k: int, codec: str, items) -> None:
        """items: iterable of (digest, parity_bytes, root_bytes)."""
        self._lru.put_many(
            ((k, codec, d), (parity, root)) for d, parity, root in items
        )

    def mark_assembled(self) -> None:
        self.assembled += 1

    def clear(self) -> None:
        self._lru.clear()
        self.assembled = 0

    def stats(self) -> dict:
        s = self._lru.stats()
        lookups = s["hits"] + s["misses"]
        return {
            "entries": s["entries"],
            "lookups": lookups,
            "hits": s["hits"],
            "inserted": s["puts"],
            "assembled": self.assembled,
            "reuse_pct": (100.0 * s["hits"] / lookups) if lookups else 0.0,
            "approx_bytes": s["approx_bytes"],
        }


def _row_memo_weigher(key, value) -> int:
    parity, root = value
    return len(parity) + len(root) + 64


def _row_memo_max_entries() -> int:
    import os

    # one entry holds a k x 512 B parity row (64 KiB at k=128): 512
    # entries bound the memo around 32 MiB worst case
    return int(os.environ.get("CELESTIA_TPU_ROW_MEMO", "512"))


_ROW_MEMO = _RowMemo(_row_memo_max_entries())


def row_memo_stats() -> dict:
    return _ROW_MEMO.stats()


def clear_row_memo() -> None:
    _ROW_MEMO.clear()


def _row_digests(square: np.ndarray) -> List[bytes]:
    """sha256 per original row (the memo keys), threaded when native."""
    from celestia_tpu.utils import native

    k = square.shape[0]
    flat = np.ascontiguousarray(square.reshape(k, -1))
    if native.available():
        d = native.sha256_batch(flat)
        return [d[i].tobytes() for i in range(k)]
    import hashlib

    return [hashlib.sha256(flat[i].tobytes()).digest() for i in range(k)]


def _row_memo_applicable() -> bool:
    """True when the memoized assembly can beat the fused pipeline for
    the active codec (see the measured scoping note above)."""
    from celestia_tpu.ops import gf256
    from celestia_tpu.utils import native

    return (not native.available()) or (
        _active_codec() == gf256.CODEC_LAGRANGE
    )


def _gf_encode_axis(X: np.ndarray) -> np.ndarray:
    """E(k) @ X over GF(256) in the active codec: uint8[k, B'] -> uint8[k, B'].

    The single primitive both memo phases need — row parity for missed
    rows and the full column extension are the same encode matrix applied
    along axis 0 (Q3 = E @ Q1 == row-extension of Q2 for a linear code,
    the rsmt2d quadrant consistency property).  The native table matmul
    threads across axes, so the byte dimension is chunked over the pool
    (zero-padding is exact: GF matmul is column-independent)."""
    from celestia_tpu.ops.gf256 import encode_matrix, encode_shares_ref
    from celestia_tpu.utils import hostpool, native

    k, Bp = X.shape
    if not native.available():
        return encode_shares_ref(X)
    E = np.ascontiguousarray(encode_matrix(k))
    T = max(1, min(hostpool.cpu_threads(), Bp // 4096))
    if T == 1:
        return native.gf_matmul_axes(E[None], np.ascontiguousarray(X)[None])[0]
    chunk = -(-Bp // T)
    pad = T * chunk - Bp
    if pad:
        X = np.concatenate(
            [X, np.zeros((k, pad), dtype=np.uint8)], axis=1
        )
    Xc = np.ascontiguousarray(
        X.reshape(k, T, chunk).transpose(1, 0, 2)
    )
    D = np.ascontiguousarray(np.broadcast_to(E, (T, k, k)))
    out = native.gf_matmul_axes(D, Xc)  # (T, k, chunk)
    out = out.transpose(1, 0, 2).reshape(k, T * chunk)
    return np.ascontiguousarray(out[:, :Bp])


def _try_memoized_extend(
    square: np.ndarray, digests: List[bytes]
) -> Optional[Tuple[ExtendedDataSquare, "DataAvailabilityHeader"]]:
    """Assemble (EDS, DAH) from the row memo, or None when coverage is too
    thin to beat the fused pipeline.

    Engages when at least a quarter of the k row-extensions are saved —
    via memo hits from earlier heights or via intra-square duplicates
    (identical padding rows extend once).  Byte-identical to the fused
    path by construction: same encode matrix, same field tables, same
    NMT/RFC-6962 reductions (pinned by tests/test_eds_cache.py)."""
    k, B = square.shape[0], square.shape[2]
    codec = _active_codec()
    entries = _ROW_MEMO.lookup_many(k, codec, digests)
    missing: "Dict[bytes, int]" = {}  # digest -> representative row
    for r, (d, e) in enumerate(zip(digests, entries)):
        if e is None and d not in missing:
            missing[d] = r
    if k - len(missing) < max(1, k // 4):
        return None
    n2 = 2 * k
    with tracing.span(
        "extend.memo", k=k, memo_hits=k - len(missing), memo_misses=len(missing)
    ):
        top = np.empty((k, n2, B), dtype=np.uint8)
        top[:, :k] = square
        parity_by_digest: "Dict[bytes, np.ndarray]" = {}
        if missing:
            reps = list(missing.values())
            data = square[reps]  # (m, k, B)
            P = _gf_encode_axis(data.transpose(1, 0, 2).reshape(k, -1))
            par = P.reshape(k, len(reps), B).transpose(1, 0, 2)  # (m, k, B)
            for i, d in enumerate(missing):
                parity_by_digest[d] = par[i]
        for r, (d, e) in enumerate(zip(digests, entries)):
            if e is not None:
                top[r, k:] = np.frombuffer(e[0], dtype=np.uint8).reshape(k, B)
            else:
                top[r, k:] = parity_by_digest[d]
        bottom = _gf_encode_axis(top.reshape(k, -1)).reshape(k, n2, B)
        eds = np.concatenate([top, bottom], axis=0)
    from celestia_tpu.utils import native

    if native.available():
        # the threaded C++ root pass over all 4k trees beats a selective
        # Python-orchestrated reduction even with most row roots memoized
        # (measured: selective batch over 3k+ trees is ~2.5x slower than
        # the full native pass) — reuse the extension, recompute roots
        with tracing.span("roots", stage="native_full_pass", trees=4 * k):
            all_roots = native.eds_nmt_roots(eds)
        row_roots = [all_roots[i].tobytes() for i in range(n2)]
        col_roots = [all_roots[n2 + i].tobytes() for i in range(n2)]
        root_by_digest = {d: row_roots[r] for d, r in missing.items()}
    else:
        # pure-Python fallback: every skipped tree is hashlib work saved —
        # memoized original rows come from the table; changed rows (deduped
        # by digest), all parity rows and all columns reduce in one batch
        own_ns = eds[..., : nmt_ops.NAMESPACE_SIZE]
        parity_ns = np.broadcast_to(nmt_ops._PARITY_NS, own_ns.shape)
        r_idx = np.arange(n2)
        in_q0 = (r_idx[:, None] < k) & (r_idx[None, :] < k)
        prefix = np.where(in_q0[..., None], own_ns, parity_ns)
        row_leaves = np.concatenate([prefix, eds], axis=-1)
        col_leaves = row_leaves.transpose(1, 0, 2)
        sel = list(missing.values()) + list(range(k, n2))
        trees = np.concatenate([row_leaves[sel], col_leaves], axis=0)
        with tracing.span("roots", stage="host_batch", trees=len(trees)):
            roots = nmt_ops.nmt_roots_host_batch(trees)
        m = len(missing)
        root_by_digest = {d: roots[i].tobytes() for i, d in enumerate(missing)}
        row_roots = []
        for d, e in zip(digests, entries):
            row_roots.append(e[1] if e is not None else root_by_digest[d])
        row_roots.extend(roots[m + j].tobytes() for j in range(k))
        col_roots = [roots[m + k + c].tobytes() for c in range(n2)]
    dah = DataAvailabilityHeader(
        tuple(row_roots),
        tuple(col_roots),
        DataAvailabilityHeader.compute_hash(row_roots, col_roots),
    )
    _ROW_MEMO.insert_many(
        k,
        codec,
        (
            (d, top[r, k:].tobytes(), root_by_digest[d])
            for d, r in missing.items()
        ),
    )
    _ROW_MEMO.mark_assembled()
    return ExtendedDataSquare(eds), dah


def _memo_populate(
    k: int, digests: List[bytes], eds_shares: np.ndarray, row_roots
) -> None:
    """Record every distinct original row of a freshly extended square."""
    codec = _active_codec()
    seen = set()
    items = []
    for r, d in enumerate(digests):
        if d in seen:
            continue
        seen.add(d)
        items.append((d, eds_shares[r, k:].tobytes(), row_roots[r]))
    _ROW_MEMO.insert_many(k, codec, items)


def extend_and_header(
    square: np.ndarray,
) -> Tuple[ExtendedDataSquare, "DataAvailabilityHeader"]:
    """The fused hot path: original square uint8[k,k,512] -> (EDS, DAH).

    One device program computes extension, 4k NMT roots and the data root
    (the reference does this as ExtendShares + NewDataAvailabilityHeader,
    app/prepare_proposal.go:65-77).  In the host regime (CPU backend —
    the tunnel-outage mode every node must survive) the same pipeline
    runs on the pooled native C++ legs instead: identical bytes, no
    multi-minute XLA CPU compile — and the row memo above skips the
    per-row work for rows whose bytes this process has extended before.
    """
    from celestia_tpu.utils.device import host_regime

    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    from celestia_tpu.da import device_plane

    if device_plane.enabled():
        # device-resident plane (specs/device_pipeline.md): one donated-
        # buffer program emits EDS + NMT level stacks + root tree; only
        # the data root and the 4k axis roots cross to the host, and the
        # level stacks stay cached device-side for DAS serving.  First in
        # the routing order so forcing the plane on (tests, smoke) wins
        # over the host-regime fast paths; any fault poisons the plane
        # one-way and THIS call falls through to the byte-identical legs
        # below.
        try:
            return device_plane.extend_and_header(square)
        except Exception as e:
            device_plane.poison(f"device-resident extend failed: {e!r}")
    digests: Optional[List[bytes]] = None
    if host_regime() and _row_memo_applicable():
        with tracing.span("row_digests", k=k):
            digests = _row_digests(square)
        memoized = _try_memoized_extend(square, digests)
        if memoized is not None:
            return memoized
    if _host_native_available():
        try:
            eds, dah = _extend_and_header_host(square)
        except Exception as e:
            # graceful degradation (specs/robustness.md): a native fault
            # mid-run pins the library OFF (one-way; loud) and this very
            # call falls through to the table-GF jax path below — byte-
            # identical output, so the block being extended still commits
            # the same data root it would have cold
            from celestia_tpu.utils import native as _native

            _native.poison(f"extend_and_header native leg failed: {e!r}")
        else:
            if digests is not None:
                _memo_populate(k, digests, eds.shares, dah.row_roots)
            return eds, dah
    from celestia_tpu.utils import devprof

    with tracing.span("extend.jax", codec=_active_codec(), k=k, fused_roots=True):
        fn = _extend_and_roots_fn(k, _active_codec())
        arr = jnp.asarray(square)
        # devprof bracket: device-track span (enqueue vs device-drain,
        # per chip).  Inactive, the dispatch is a shared no-op and the
        # result stays ASYNC — the hot path keeps its fire-and-forget
        # shape.
        d = devprof.dispatch("extend_and_roots", k=k, codec=_active_codec())
        eds_d, row_roots, col_roots, data_root = d.done(fn(arr))
    # cost accounting OUTSIDE both the device bracket and the traced
    # extend.jax span: the one-time AOT compile must inflate neither
    # the device span nor the phase ms bench_check now watches
    devprof.note_compile("extend_and_roots", fn, (arr,))
    eds = ExtendedDataSquare(eds_d)  # stays on device until shares are read
    with tracing.span("roots", stage="fetch"):
        # materializing the root arrays forces the (async) device values
        # to host — on an attached chip this span IS the root fetch
        rr = np.asarray(row_roots)
        cc = np.asarray(col_roots)
        dah = DataAvailabilityHeader(
            tuple(rr[i].tobytes() for i in range(rr.shape[0])),
            tuple(cc[i].tobytes() for i in range(cc.shape[0])),
            np.asarray(data_root).tobytes(),
        )
    if digests is not None:
        # host-regime jax fallback: the "device" array is CPU-backed, so
        # materializing the shares is a host copy, not a tunnel transfer
        _memo_populate(k, digests, eds.shares, dah.row_roots)
    return eds, dah


def extend_and_header_breakdown(square: np.ndarray):
    """extend_and_header with the transfer budget split out: returns
    (eds, dah, {"upload_ms", "compute_ms", "fetch_ms"}).

    Three device syncs instead of one fused call, so the total is a few
    RTTs WORSE than extend_and_header — use it to attribute time (bench
    breakdown, SURVEY §7 hard part c), never on the hot path."""
    from celestia_tpu.utils.telemetry import clock as _clock

    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    from celestia_tpu.utils import devprof

    t0 = _clock()
    dev = jax.device_put(jnp.asarray(square))
    dev.block_until_ready()
    t1 = _clock()
    fn = _extend_and_roots_fn(k, _active_codec())
    out = fn(dev)
    jax.block_until_ready(out)
    t2 = _clock()
    eds_d, row_roots, col_roots, data_root = out
    rr = np.asarray(row_roots)
    cc = np.asarray(col_roots)
    droot = np.asarray(data_root).tobytes()
    t3 = _clock()
    # cost accounting after the LAST timestamp: the one-time AOT
    # compile must not leak into any breakdown window
    devprof.note_compile("extend_and_roots", fn, (dev,))
    dah = DataAvailabilityHeader(
        tuple(rr[i].tobytes() for i in range(rr.shape[0])),
        tuple(cc[i].tobytes() for i in range(cc.shape[0])),
        droot,
    )
    return ExtendedDataSquare(eds_d), dah, {
        "upload_ms": (t1 - t0) * 1000.0,
        "compute_ms": (t2 - t1) * 1000.0,
        "fetch_ms": (t3 - t2) * 1000.0,
    }


def new_data_availability_header(eds: ExtendedDataSquare) -> DataAvailabilityHeader:
    """da.NewDataAvailabilityHeader parity: roots + hash from an existing EDS.

    Host regime: the 4k independent NMT trees shard across the process
    worker pool (ops/nmt.py eds_nmt_roots_host) instead of compiling the
    XLA CPU program — same bytes, minutes less latency at k=128."""
    roots = None
    if _host_native_available():
        try:
            with tracing.span("roots", stage="host_pool", trees=2 * eds.width):
                roots = nmt_ops.eds_nmt_roots_host(eds.shares)
        except Exception as e:
            # same one-way degradation as extend_and_header: poison the
            # native leg and recompute on the jax path (identical bytes)
            from celestia_tpu.utils import native as _native

            _native.poison(f"eds_nmt_roots native leg failed: {e!r}")
    if roots is None:
        with tracing.span("roots", stage="jax"):
            # the standalone devprof-instrumented device entry
            # (ops/nmt.py): device-track timing + XLA cost accounting
            # when profiling is armed, a plain jitted call otherwise
            roots = nmt_ops.eds_nmt_roots_device(eds.shares)
    rows = tuple(roots[0, i].tobytes() for i in range(roots.shape[1]))
    cols = tuple(roots[1, i].tobytes() for i in range(roots.shape[1]))
    return DataAvailabilityHeader(
        rows, cols, DataAvailabilityHeader.compute_hash(rows, cols)
    )


def extend_block(square: Square) -> Tuple[ExtendedDataSquare, DataAvailabilityHeader]:
    """app.ExtendBlock parity (extend_block.go:14-26): square -> (EDS, DAH)."""
    k = square.size
    arr = square.to_array().reshape(k, k, SHARE_SIZE)
    return extend_and_header(arr)


# serializes the first computation of the min DAH; the PR 4 worker pool
# made the old bare module global racy (two threads could both see None
# and compute concurrently — benign for the value, but the unsynchronized
# write was a data race by contract)
_min_dah_lock = threading.Lock()


def min_data_availability_header() -> DataAvailabilityHeader:
    """DAH of the minimal (empty) square: one tail-padding share
    (data_availability_header.go:179).

    Cached as the first resident of the content-addressed EDS cache
    (da/eds_cache.py) — codec-aware by key, so a test that switches the
    active codec can never read the other codec's min DAH, and lock-
    guarded so pool workers race neither the computation nor the insert."""
    from celestia_tpu.da import eds_cache

    key = eds_cache.min_dah_key(_active_codec())
    hit = eds_cache.CACHE.peek(key)  # peek: keep hit-rate stats about blocks
    if hit is not None:
        return hit[1]
    with _min_dah_lock:
        hit = eds_cache.CACHE.peek(key)
        if hit is not None:
            return hit[1]
        from celestia_tpu.da.square import build

        square, _, _ = build([])
        eds, dah = extend_block(square)
        eds_cache.put(key, eds, dah)
        return dah
