"""Extended data square + DataAvailabilityHeader: the block-extension hot path.

Behavioral parity with /root/reference/pkg/da/data_availability_header.go
(ExtendShares :65-75, NewDataAvailabilityHeader :44-63, Hash :92-108,
ValidateBasic :134-177, MinDataAvailabilityHeader :179) and
app/extend_block.go:14-32 — redesigned as one fused, jit-compiled device
program: RS-extend (ops/rs.py bit-matmuls) -> all 4k NMT axis roots
(ops/nmt.py level-synchronous reduction) -> RFC-6962 data root, in a single
XLA executable per square size.  This runs twice per block per validator
(PrepareProposal / ProcessProposal) and is the BASELINE.json north star.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from celestia_tpu.appconsts import (
    DEFAULT_SQUARE_SIZE_UPPER_BOUND,
    SHARE_SIZE,
    is_power_of_two,
)
from celestia_tpu.da.square import Square
from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.ops import rs
from celestia_tpu.ops.gf256 import active_codec as _active_codec
from celestia_tpu.ops.gf256 import encode_matrix_bits

NMT_ROOT_SIZE = nmt_ops.NMT_DIGEST_SIZE  # 90
DATA_ROOT_SIZE = 32


class ExtendedDataSquare:
    """A 2k x 2k erasure-extended share square (rsmt2d.ExtendedDataSquare parity).

    Holds the share tensor uint8[2k, 2k, 512]; Q0 (top-left k x k) is the
    original data square.
    """

    def __init__(self, shares):
        # Accepts a host array OR a device (jax) array.  A device-resident
        # EDS stays on the device until something actually reads the share
        # bytes (proof generation, gossip): PrepareProposal/ProcessProposal
        # only consume the roots, so the ~8-33 MiB device->host transfer
        # drops out of the block hot path (SURVEY §7 hard part c).
        if isinstance(shares, np.ndarray) or not hasattr(shares, "shape"):
            # host-coercible input (ndarray, list, tuple, ...)
            shares = np.asarray(shares, dtype=np.uint8)
        elif shares.dtype != np.uint8:  # device array with wrong dtype
            raise ValueError(f"EDS shares must be uint8, got {shares.dtype}")
        n = shares.shape[0]
        if shares.shape != (n, n, SHARE_SIZE) or n % 2 or not is_power_of_two(n // 2):
            raise ValueError(f"invalid EDS shape {shares.shape}")
        self._shares = shares

    @property
    def shares(self) -> np.ndarray:
        if not isinstance(self._shares, np.ndarray):
            self._shares = np.asarray(self._shares).astype(np.uint8, copy=False)
        return self._shares

    @property
    def width(self) -> int:
        # shape is metadata — never forces a device->host transfer
        return self._shares.shape[0]

    @property
    def square_size(self) -> int:
        """Original (unextended) square width k."""
        return self.width // 2

    def row(self, r: int) -> np.ndarray:
        return self.shares[r]

    def col(self, c: int) -> np.ndarray:
        return self.shares[:, c]

    def quadrant(self, q: int) -> np.ndarray:
        k = self.square_size
        r, c = divmod(q, 2)
        return self.shares[r * k : (r + 1) * k, c * k : (c + 1) * k]

    def flattened_original(self) -> np.ndarray:
        """Q0 as uint8[k*k, 512] (row-major original shares)."""
        k = self.square_size
        return self.quadrant(0).reshape(k * k, SHARE_SIZE)


@lru_cache(maxsize=None)
def _extend_and_roots_fn(k: int, codec: str):
    """Jitted fused pipeline for square size k:
    square uint8[k,k,512] -> (eds, row_roots[2k,90], col_roots[2k,90], data_root[32])."""
    G = jnp.asarray(encode_matrix_bits(k, codec))

    def run(square: jnp.ndarray):
        eds = rs._extend(square, G)
        roots = nmt_ops.eds_nmt_roots(eds)  # (2, 2k, 90)
        all_roots = roots.reshape(4 * k, NMT_ROOT_SIZE)
        data_root = nmt_ops.rfc6962_root_pow2(all_roots)
        return eds, roots[0], roots[1], data_root

    return jax.jit(run)


@dataclass(frozen=True)
class DataAvailabilityHeader:
    """Row/column NMT roots + memoized hash (= the block's data root)."""

    row_roots: Tuple[bytes, ...]
    col_roots: Tuple[bytes, ...]
    _hash: bytes

    @property
    def hash(self) -> bytes:
        return self._hash

    @property
    def square_size(self) -> int:
        return len(self.row_roots) // 2

    def validate_basic(self) -> None:
        """dah ValidateBasic parity: extended square bounds + root shapes +
        hash consistency (data_availability_header.go:134-177)."""
        n = len(self.row_roots)
        if n == 0 or n != len(self.col_roots):
            raise ValueError("row/col root counts must match and be non-empty")
        k = n // 2
        if n % 2 or not is_power_of_two(k):
            raise ValueError(f"extended square width {n} must be 2 * power-of-two")
        if k > DEFAULT_SQUARE_SIZE_UPPER_BOUND:
            raise ValueError(
                f"square size {k} exceeds upper bound {DEFAULT_SQUARE_SIZE_UPPER_BOUND}"
            )
        for r in (*self.row_roots, *self.col_roots):
            if len(r) != NMT_ROOT_SIZE:
                raise ValueError(f"NMT root must be {NMT_ROOT_SIZE} bytes")
        if self.compute_hash(self.row_roots, self.col_roots) != self._hash:
            raise ValueError("DAH hash does not match its roots")

    @staticmethod
    def compute_hash(row_roots, col_roots) -> bytes:
        return nmt_ops.rfc6962_root_np(list(row_roots) + list(col_roots)).tobytes()

    def to_bytes(self) -> bytes:
        """Deterministic wire form: counts + concatenated roots."""
        out = bytearray()
        out += len(self.row_roots).to_bytes(4, "big")
        for r in self.row_roots:
            out += r
        out += len(self.col_roots).to_bytes(4, "big")
        for c in self.col_roots:
            out += c
        return bytes(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DataAvailabilityHeader":
        n_rows = int.from_bytes(raw[:4], "big")
        pos = 4
        rows = []
        for _ in range(n_rows):
            rows.append(raw[pos : pos + NMT_ROOT_SIZE])
            pos += NMT_ROOT_SIZE
        n_cols = int.from_bytes(raw[pos : pos + 4], "big")
        pos += 4
        cols = []
        for _ in range(n_cols):
            cols.append(raw[pos : pos + NMT_ROOT_SIZE])
            pos += NMT_ROOT_SIZE
        if pos != len(raw):
            raise ValueError("trailing bytes in DAH encoding")
        dah = cls(tuple(rows), tuple(cols), cls.compute_hash(rows, cols))
        dah.validate_basic()
        return dah


def extend_shares(shares: np.ndarray) -> ExtendedDataSquare:
    """da.ExtendShares parity: uint8[n, 512] (n a perfect power-of-4 count)
    -> ExtendedDataSquare."""
    shares = np.asarray(shares, dtype=np.uint8)
    n = shares.shape[0]
    k = int(round(n**0.5))
    if k * k != n or not is_power_of_two(k):
        raise ValueError(f"share count {n} must be a square of a power of two")
    square = shares.reshape(k, k, SHARE_SIZE)
    eds = np.asarray(rs.extend_square(square))
    return ExtendedDataSquare(eds)


def _host_native_available() -> bool:
    """True when the host-regime fast path applies: the default backend
    is the CPU (device tunnel down / host-only deployment) and the
    native pooled pipeline is present."""
    from celestia_tpu.utils import native
    from celestia_tpu.utils.device import host_regime

    return host_regime() and native.available()


def _extend_and_header_host(
    square: np.ndarray,
) -> Tuple[ExtendedDataSquare, "DataAvailabilityHeader"]:
    """Host-regime ExtendBlock: the pooled native C++ pipeline with the
    extend->roots overlap (byte-identical to the device program — pinned
    by tests/test_leopard_codec.py / test_golden_vectors.py)."""
    from celestia_tpu.ops import gf256
    from celestia_tpu.utils import native

    if gf256.active_codec() == gf256.CODEC_LEOPARD:
        eds, roots, data_root = native.extend_block_leopard_cpu(square)
    else:
        eds, roots, data_root = native.extend_block_cpu(square)
    n2 = 2 * square.shape[0]
    dah = DataAvailabilityHeader(
        tuple(roots[i].tobytes() for i in range(n2)),
        tuple(roots[n2 + i].tobytes() for i in range(n2)),
        data_root.tobytes(),
    )
    return ExtendedDataSquare(eds), dah


def extend_and_header(
    square: np.ndarray,
) -> Tuple[ExtendedDataSquare, "DataAvailabilityHeader"]:
    """The fused hot path: original square uint8[k,k,512] -> (EDS, DAH).

    One device program computes extension, 4k NMT roots and the data root
    (the reference does this as ExtendShares + NewDataAvailabilityHeader,
    app/prepare_proposal.go:65-77).  In the host regime (CPU backend —
    the tunnel-outage mode every node must survive) the same pipeline
    runs on the pooled native C++ legs instead: identical bytes, no
    multi-minute XLA CPU compile.
    """
    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    if _host_native_available():
        return _extend_and_header_host(square)
    eds_d, row_roots, col_roots, data_root = _extend_and_roots_fn(k, _active_codec())(
        jnp.asarray(square)
    )
    eds = ExtendedDataSquare(eds_d)  # stays on device until shares are read
    rr = np.asarray(row_roots)
    cc = np.asarray(col_roots)
    dah = DataAvailabilityHeader(
        tuple(rr[i].tobytes() for i in range(rr.shape[0])),
        tuple(cc[i].tobytes() for i in range(cc.shape[0])),
        np.asarray(data_root).tobytes(),
    )
    return eds, dah


def extend_and_header_breakdown(square: np.ndarray):
    """extend_and_header with the transfer budget split out: returns
    (eds, dah, {"upload_ms", "compute_ms", "fetch_ms"}).

    Three device syncs instead of one fused call, so the total is a few
    RTTs WORSE than extend_and_header — use it to attribute time (bench
    breakdown, SURVEY §7 hard part c), never on the hot path."""
    import time as _t

    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    t0 = _t.time()
    dev = jax.device_put(jnp.asarray(square))
    dev.block_until_ready()
    t1 = _t.time()
    out = _extend_and_roots_fn(k, _active_codec())(dev)
    jax.block_until_ready(out)
    t2 = _t.time()
    eds_d, row_roots, col_roots, data_root = out
    rr = np.asarray(row_roots)
    cc = np.asarray(col_roots)
    droot = np.asarray(data_root).tobytes()
    t3 = _t.time()
    dah = DataAvailabilityHeader(
        tuple(rr[i].tobytes() for i in range(rr.shape[0])),
        tuple(cc[i].tobytes() for i in range(cc.shape[0])),
        droot,
    )
    return ExtendedDataSquare(eds_d), dah, {
        "upload_ms": (t1 - t0) * 1000.0,
        "compute_ms": (t2 - t1) * 1000.0,
        "fetch_ms": (t3 - t2) * 1000.0,
    }


_eds_nmt_roots_jit = jax.jit(nmt_ops.eds_nmt_roots)  # one cache for all calls


def new_data_availability_header(eds: ExtendedDataSquare) -> DataAvailabilityHeader:
    """da.NewDataAvailabilityHeader parity: roots + hash from an existing EDS.

    Host regime: the 4k independent NMT trees shard across the process
    worker pool (ops/nmt.py eds_nmt_roots_host) instead of compiling the
    XLA CPU program — same bytes, minutes less latency at k=128."""
    if _host_native_available():
        roots = nmt_ops.eds_nmt_roots_host(eds.shares)
    else:
        roots = np.asarray(_eds_nmt_roots_jit(jnp.asarray(eds.shares)))
    rows = tuple(roots[0, i].tobytes() for i in range(roots.shape[1]))
    cols = tuple(roots[1, i].tobytes() for i in range(roots.shape[1]))
    return DataAvailabilityHeader(
        rows, cols, DataAvailabilityHeader.compute_hash(rows, cols)
    )


def extend_block(square: Square) -> Tuple[ExtendedDataSquare, DataAvailabilityHeader]:
    """app.ExtendBlock parity (extend_block.go:14-26): square -> (EDS, DAH)."""
    k = square.size
    arr = square.to_array().reshape(k, k, SHARE_SIZE)
    return extend_and_header(arr)


_min_dah_cache: Optional[DataAvailabilityHeader] = None


def min_data_availability_header() -> DataAvailabilityHeader:
    """DAH of the minimal (empty) square: one tail-padding share
    (data_availability_header.go:179)."""
    global _min_dah_cache
    if _min_dah_cache is None:
        from celestia_tpu.da.square import build

        square, _, _ = build([])
        _, dah = extend_block(square)
        _min_dah_cache = dah
    return _min_dah_cache
