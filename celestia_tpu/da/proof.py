"""Inclusion proofs: NMT range proofs + merkle proofs to the data root.

Parity with /root/reference/pkg/proof/: NewTxInclusionProof (proof.go:20-42),
NewShareInclusionProof (proof.go:55-167) and their verification — proving
that a range of shares (or a tx's compact shares) is committed by the
block's data root.  A share proof is: for each row the range touches, an NMT
range proof of those shares against the row root, plus an RFC-6962 merkle
proof of each row root against the data root (over the 4k row+col roots).

Proof generation reads the device-computed NMT level stack (ops/nmt.py
nmt_level_stack); verification is host-side hashlib (proofs are verified by
light clients, not validators).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from celestia_tpu.appconsts import (
    CONTINUATION_COMPACT_SHARE_CONTENT_SIZE,
    FIRST_COMPACT_SHARE_CONTENT_SIZE,
    NAMESPACE_SIZE,
)
from celestia_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_tpu.da.namespace import TRANSACTION_NAMESPACE, Namespace
from celestia_tpu.da.shares import _varint
from celestia_tpu.da.square import Square
from celestia_tpu.ops import nmt as nmt_ops


# ---------------------------------------------------------------------------
# NMT range proofs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class NmtRangeProof:
    """Proof that leaves [start, end) belong to an NMT with a given root."""

    start: int
    end: int
    nodes: Tuple[bytes, ...]  # sibling digests, traversal order

    def verify(
        self, root: bytes, leaves: Sequence[bytes], tree_size: int
    ) -> bool:
        """Recompute the root from the namespace-prefixed leaves + siblings.

        ``leaves`` are the ns-prefixed leaf payloads for [start, end).
        """
        if not 0 <= self.start < self.end <= tree_size:
            return False
        if len(leaves) != self.end - self.start:
            return False
        nodes = list(self.nodes)
        leaf_digests = [nmt_ops.leaf_digest_np(l) for l in leaves]

        def compute(lo: int, hi: int) -> Optional[bytes]:
            if lo >= self.end or hi <= self.start:  # disjoint: sibling node
                if not nodes:
                    return None
                return nodes.pop(0)
            if hi - lo == 1:
                return leaf_digests[lo - self.start]
            mid = (lo + hi) // 2
            l = compute(lo, mid)
            r = compute(mid, hi)
            if l is None or r is None:
                return None
            return nmt_ops.combine_digests_np(l, r)

        got = compute(0, tree_size)
        return got == root and not nodes

    def sibling_namespace_bounds(
        self, tree_size: int, namespace: bytes, check_right: bool = True
    ) -> bool:
        """Walk the proof's sibling digests in the SAME traversal order
        verify() consumes them and check their embedded namespace ranges
        against the target: every left sibling must end below it, and
        (when ``check_right``) every right sibling must start above it.
        The single source of truth for sibling ordering — completeness and
        absence verification both ride on it."""
        nodes = list(self.nodes)

        def walk(lo: int, hi: int) -> bool:
            if lo >= self.end or hi <= self.start:
                node = nodes.pop(0)
                if hi <= self.start:  # entirely left of the range
                    return node[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE] < namespace
                if check_right:  # entirely right
                    return node[:NAMESPACE_SIZE] > namespace
                return True
            if hi - lo == 1:
                return True
            mid = (lo + hi) // 2
            return walk(lo, mid) and walk(mid, hi)

        return walk(0, tree_size)

    def verify_complete_namespace(
        self, root: bytes, leaves: Sequence[bytes], tree_size: int,
        namespace: bytes,
    ) -> bool:
        """Verify the range AND that it covers every leaf of ``namespace``
        in the tree: each sibling subtree left of the range must end below
        the namespace, each right sibling must start above it (their
        min/max namespaces are embedded in the 90-byte digests — the NMT
        property that makes per-namespace retrieval trustlessly complete)."""
        if not self.verify(root, leaves, tree_size):
            return False
        for l in leaves:
            if l[:NAMESPACE_SIZE] != namespace:
                return False  # foreign leaf smuggled into the range
        return self.sibling_namespace_bounds(tree_size, namespace)


def nmt_range_proof_from_levels(
    levels: List[np.ndarray], start: int, end: int
) -> NmtRangeProof:
    """Build a range proof from a tree's level stack (device output).

    levels[0] = leaf digests (n, 90), levels[-1] = root (1, 90).
    """
    n = levels[0].shape[0]
    nodes: List[bytes] = []

    def walk(lo: int, hi: int, level: int):
        if lo >= end or hi <= start:
            # disjoint aligned span: one sibling digest from the stack
            nodes.append(levels[level][lo >> level].tobytes())
            return
        if hi - lo == 1:
            return  # in-range leaf, provided by the verifier
        mid = (lo + hi) // 2
        walk(lo, mid, level - 1)
        walk(mid, hi, level - 1)

    walk(0, n, len(levels) - 1)
    return NmtRangeProof(start, end, tuple(nodes))


# ---------------------------------------------------------------------------
# RFC-6962 merkle proofs (tendermint split rule) for the data root
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MerkleProof:
    index: int
    total: int
    aunts: Tuple[bytes, ...]  # bottom-up sibling hashes

    def verify(self, root: bytes, leaf: bytes) -> bool:
        import hashlib

        if not 0 <= self.index < self.total:
            return False
        h = hashlib.sha256(b"\x00" + leaf).digest()
        idx, total = self.index, self.total
        aunts = list(self.aunts)

        def rec(h, idx, total, aunts):
            import hashlib

            if total == 1:
                return h if not aunts else None
            split = 1
            while split * 2 < total:
                split *= 2
            if not aunts:
                return None
            aunt = aunts.pop()
            if idx < split:
                left = rec(h, idx, split, aunts)
                if left is None:
                    return None
                return hashlib.sha256(b"\x01" + left + aunt).digest()
            right = rec(h, idx - split, total - split, aunts)
            if right is None:
                return None
            return hashlib.sha256(b"\x01" + aunt + right).digest()

        # aunts are stored bottom-up; rec consumes from the END (top-down)
        got = rec(h, idx, total, aunts)
        return got == root and not aunts


def merkle_level_tree(leaves: Sequence[bytes]) -> List[np.ndarray]:
    """All levels of the RFC-6962 tree over a POWER-OF-TWO number of
    equal-length leaves: ``[leaf hashes (n, 32), (n/2, 32), ..., root
    (1, 32)]``, hashed through the threaded host batch kernel.

    For power-of-two counts the tendermint split rule (largest power of
    two strictly below n) degenerates to n/2 at every level, so the tree
    is perfectly balanced and the proof for ANY index is a pure
    level-stack extraction (:func:`merkle_proof_from_levels`) — the DAS
    serving plane builds this ONCE per block over the DAH's 4k axis
    roots instead of re-hashing the whole tree per sampled cell.
    Byte-identical to :func:`merkle_proof` (pinned by tests/test_das.py).
    """
    from celestia_tpu.ops.sha256 import sha256_batch_host

    n = len(leaves)
    if n == 0 or n & (n - 1):
        raise ValueError(f"leaf count must be a power of two, got {n}")
    arr = np.frombuffer(b"".join(leaves), dtype=np.uint8).reshape(n, -1)
    zero = np.zeros((n, 1), dtype=np.uint8)
    levels = [sha256_batch_host(np.concatenate([zero, arr], axis=-1))]
    while levels[-1].shape[0] > 1:
        cur = levels[-1]
        left, right = cur[0::2], cur[1::2]
        one = np.ones((left.shape[0], 1), dtype=np.uint8)
        levels.append(
            sha256_batch_host(np.concatenate([one, left, right], axis=-1))
        )
    for lv in levels:
        lv.flags.writeable = False  # served from a shared cache
    return levels


def merkle_proof_from_levels(
    levels: List[np.ndarray], index: int
) -> MerkleProof:
    """Extract the proof for ``index`` from a :func:`merkle_level_tree`
    stack: the level-``j`` aunt is the sibling subtree hash
    ``levels[j][(index >> j) ^ 1]`` (aunts stored bottom-up, exactly the
    order :func:`merkle_proof` records them in)."""
    total = levels[0].shape[0]
    if not 0 <= index < total:
        raise ValueError(f"index {index} out of range for {total} leaves")
    aunts = tuple(
        levels[j][(index >> j) ^ 1].tobytes() for j in range(len(levels) - 1)
    )
    return MerkleProof(index, total, aunts)


def merkle_proof(leaves: Sequence[bytes], index: int) -> MerkleProof:
    """Proof for leaf ``index`` over arbitrary-count leaves (tendermint
    simple merkle, split = largest power of two < n)."""
    import hashlib

    aunts: List[bytes] = []

    def rec(items: List[bytes], idx: int) -> bytes:
        if len(items) == 1:
            return hashlib.sha256(b"\x00" + items[0]).digest()
        split = 1
        while split * 2 < len(items):
            split *= 2
        if idx < split:
            h = rec(items[:split], idx)
            other = _subtree_hash(items[split:])
        else:
            h = rec(items[split:], idx - split)
            other = _subtree_hash(items[:split])
        aunts.append(other)
        return h  # unused

    def _subtree_hash(items: List[bytes]) -> bytes:
        return bytes(nmt_ops.rfc6962_root_np(items))

    rec(list(leaves), index)
    return MerkleProof(index, len(leaves), tuple(aunts))


# ---------------------------------------------------------------------------
# Share / tx inclusion proofs (pkg/proof parity)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RowShareProof:
    row: int  # EDS row index
    start_col: int
    end_col: int
    nmt_proof: NmtRangeProof
    root_proof: MerkleProof  # row root -> data root


@dataclass(frozen=True)
class ShareInclusionProof:
    """Proof that shares [start, end) of the ORIGINAL square are committed
    by the data root (NewShareInclusionProof, proof.go:55-167)."""

    start: int
    end: int
    square_size: int
    namespace: bytes
    shares: Tuple[bytes, ...]  # the raw 512-B shares being proven
    row_proofs: Tuple[RowShareProof, ...]
    row_roots: Tuple[bytes, ...]

    def verify(self, data_root: bytes) -> bool:
        k = self.square_size
        if not 0 <= self.start < self.end <= k * k:
            return False
        # The row proofs must cover EXACTLY the declared [start, end) range:
        # contiguous rows, correct column slices, row-root merkle indexes
        # bound to those rows (over the 4k row+col roots).  Without this
        # binding a prover could present valid shares from different
        # positions than claimed.
        first_row, last_row = self.start // k, (self.end - 1) // k
        expected_rows = list(range(first_row, last_row + 1))
        if len(self.row_proofs) != len(expected_rows):
            return False
        if len(self.row_roots) != len(self.row_proofs):
            return False
        share_i = 0
        for rp, root, row in zip(self.row_proofs, self.row_roots, expected_rows):
            if rp.row != row:
                return False
            want_c0 = self.start - row * k if row == first_row else 0
            want_c1 = self.end - row * k if row == last_row else k
            if (rp.start_col, rp.end_col) != (want_c0, want_c1):
                return False
            if (rp.nmt_proof.start, rp.nmt_proof.end) != (want_c0, want_c1):
                return False
            if rp.root_proof.index != row or rp.root_proof.total != 4 * k:
                return False
            n_shares = rp.end_col - rp.start_col
            row_shares = self.shares[share_i : share_i + n_shares]
            if len(row_shares) != n_shares:
                return False
            share_i += n_shares
            # ns-prefixed leaves (Q0 rule: own namespace)
            leaves = [s[:NAMESPACE_SIZE] + s for s in row_shares]
            if not rp.nmt_proof.verify(root, leaves, 2 * k):
                return False
            if not rp.root_proof.verify(data_root, root):
                return False
        return share_i == len(self.shares)

    # -- wire form (JSON-safe dict) — lets the node API serve proofs
    #    (pkg/proof/querier.go routes) and clients re-verify them --------

    def to_dict(self) -> dict:
        return {
            "start": self.start,
            "end": self.end,
            "square_size": self.square_size,
            "namespace": self.namespace.hex(),
            "shares": [s.hex() for s in self.shares],
            "row_roots": [r.hex() for r in self.row_roots],
            "row_proofs": [
                {
                    "row": rp.row,
                    "start_col": rp.start_col,
                    "end_col": rp.end_col,
                    "nmt": {
                        "start": rp.nmt_proof.start,
                        "end": rp.nmt_proof.end,
                        "nodes": [n.hex() for n in rp.nmt_proof.nodes],
                    },
                    "root": {
                        "index": rp.root_proof.index,
                        "total": rp.root_proof.total,
                        "aunts": [a.hex() for a in rp.root_proof.aunts],
                    },
                }
                for rp in self.row_proofs
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ShareInclusionProof":
        return cls(
            start=int(d["start"]),
            end=int(d["end"]),
            square_size=int(d["square_size"]),
            namespace=bytes.fromhex(d["namespace"]),
            shares=tuple(bytes.fromhex(s) for s in d["shares"]),
            row_proofs=tuple(
                RowShareProof(
                    row=int(rp["row"]),
                    start_col=int(rp["start_col"]),
                    end_col=int(rp["end_col"]),
                    nmt_proof=NmtRangeProof(
                        start=int(rp["nmt"]["start"]),
                        end=int(rp["nmt"]["end"]),
                        nodes=tuple(
                            bytes.fromhex(n) for n in rp["nmt"]["nodes"]
                        ),
                    ),
                    root_proof=MerkleProof(
                        index=int(rp["root"]["index"]),
                        total=int(rp["root"]["total"]),
                        aunts=tuple(
                            bytes.fromhex(a) for a in rp["root"]["aunts"]
                        ),
                    ),
                )
                for rp in d["row_proofs"]
            ),
            row_roots=tuple(bytes.fromhex(r) for r in d["row_roots"]),
        )


def new_share_inclusion_proof(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    start: int,
    end: int,
) -> ShareInclusionProof:
    """Prove original-square shares [start, end) to the data root."""
    k = eds.square_size
    if not 0 <= start < end <= k * k:
        raise ValueError(f"share range [{start}, {end}) out of square bounds")
    all_roots = list(dah.row_roots) + list(dah.col_roots)
    shares: List[bytes] = []
    row_proofs: List[RowShareProof] = []
    row_roots: List[bytes] = []
    first_row, last_row = start // k, (end - 1) // k
    # One batched level-stack computation over all touched rows (leaf/combine
    # kernels are batch-aware over leading dims): log2(2k) device dispatches
    # total instead of rows * log2(2k).
    rows_block = jnp.asarray(eds.shares[first_row : last_row + 1])  # (R, 2k, 512)
    own_ns = rows_block[..., :NAMESPACE_SIZE]
    parity = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(b"\xff" * NAMESPACE_SIZE, dtype=np.uint8)),
        own_ns.shape,
    )
    in_q0 = jnp.arange(2 * k)[None, :, None] < k  # touched rows are all < k
    prefix = jnp.where(in_q0, own_ns, parity)
    leaves_block = jnp.concatenate([prefix, rows_block], axis=-1)
    batched_levels = [np.asarray(lv) for lv in nmt_ops.nmt_level_stack(leaves_block)]
    for row in range(first_row, last_row + 1):
        c0 = start - row * k if row == first_row else 0
        c1 = end - row * k if row == last_row else k
        levels = [lv[row - first_row] for lv in batched_levels]
        nmt_proof = nmt_range_proof_from_levels(levels, c0, c1)
        root_proof = merkle_proof(all_roots, row)
        for c in range(c0, c1):
            shares.append(eds.shares[row, c].tobytes())
        row_proofs.append(RowShareProof(row, c0, c1, nmt_proof, root_proof))
        row_roots.append(dah.row_roots[row])
    ns = Namespace(shares[0][:NAMESPACE_SIZE]) if shares else TRANSACTION_NAMESPACE
    return ShareInclusionProof(
        start, end, k, ns.raw, tuple(shares), tuple(row_proofs), tuple(row_roots)
    )


# --- tx -> share range (go-square Builder.FindTxShareRange parity) ----------


def _compact_offset_to_share(off: int) -> int:
    if off < FIRST_COMPACT_SHARE_CONTENT_SIZE:
        return 0
    return 1 + (off - FIRST_COMPACT_SHARE_CONTENT_SIZE) // CONTINUATION_COMPACT_SHARE_CONTENT_SIZE


def tx_share_range(
    normal_txs: Sequence[bytes], wrapped_pfbs: Sequence[bytes], tx_index: int
) -> Tuple[int, int]:
    """Share range (in square coordinates) occupied by block tx
    ``tx_index`` — normal txs first (TX namespace), then wrapped PFB txs
    (PFB namespace, offset by the TX-namespace share count)."""
    from celestia_tpu.da.shares import compact_shares_needed

    n_tx_shares = compact_shares_needed(normal_txs)
    if tx_index < len(normal_txs):
        seq, idx, base = normal_txs, tx_index, 0
    else:
        seq, idx, base = wrapped_pfbs, tx_index - len(normal_txs), n_tx_shares
        if idx >= len(wrapped_pfbs):
            raise IndexError(f"tx index {tx_index} out of range")
    off = 0
    for i, t in enumerate(seq):
        unit = len(_varint(len(t))) + len(t)
        if i == idx:
            return base + _compact_offset_to_share(off), base + _compact_offset_to_share(
                off + unit - 1
            ) + 1
        off += unit
    raise IndexError(f"tx index {tx_index} out of range")


def new_tx_inclusion_proof(
    square: Square,
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    normal_txs: Sequence[bytes],
    wrapped_pfbs: Sequence[bytes],
    tx_index: int,
) -> ShareInclusionProof:
    """NewTxInclusionProof parity (proof.go:20-42): prove the compact shares
    containing block tx ``tx_index``."""
    start, end = tx_share_range(normal_txs, wrapped_pfbs, tx_index)
    return new_share_inclusion_proof(eds, dah, start, end)
