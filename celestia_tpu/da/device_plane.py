"""Device-resident DA plane: EDS, NMT levels and the root tree stay on-chip.

Every earlier stage of the pipeline re-crossed the PCIe wall: the fused
extend+roots program fetched its roots per array, the standalone device
root pass (ops/nmt.py eds_nmt_roots_device) DISCARDED the inner NMT
levels so DAS serving re-hashed whole rows host-side, and proof
generation re-uploaded shares it had just fetched.  This module makes
the proposal->commit->serve lifecycle device-resident end to end
("On the Encoding Process in Decentralized Systems", arxiv 2408.15203:
the encode pipeline should produce its downstream artifacts in place,
not round-trip them through a host barrier):

* ONE donated-buffer program (:func:`_extend_levels_fn`) takes the
  original square and emits the EDS, the full per-row/per-column NMT
  level stacks and the RFC-6962 root-tree levels — no intermediate host
  fetch.  The only eager D2H on the proposal path is the 32-byte data
  root; the 4k axis roots follow in one lazily-issued tuple fetch (the
  DAH is a host object), and the shares/levels never cross at all.
* The device buffers ride a :class:`DevicePlaneEntry` handle cached in
  da/eds_cache.py beside the content-addressed (EDS, DAH) entry, with
  explicit byte-budget accounting from array SHAPES (weighing an entry
  must never force a transfer).
* DAS proofs become pure gathers (:func:`sample_proofs_batch`): proof-
  path indices are host integer arithmetic, the digests are gathered on
  the device, and ONE batched ``device_get`` fetches every proof node +
  share of the batch — never a re-hash.  Byte-identity with the host
  prover (da/das.py ``_sample_proof_uncached``) is pinned by
  tests/test_device_plane.py for both codecs.

Degradation ladder (specs/robustness.md): any device fault poisons the
plane ONE-WAY for the rest of the process — same contract as
utils/native.py — and every caller falls back to the byte-identical
host paths (da/dah.py legs, da/das.py host prover).  An entry evicted
from the byte budget is just a miss: the host fallback serves identical
proofs (pinned by the eviction test).

Donation rule: the input square is donated (``donate_argnums``) on
accelerator backends so XLA can reuse its pages; on the CPU backend XLA
cannot alias host buffers and would warn per compile, so the flag is
dropped there — output bytes are identical either way.

Activation (``CELESTIA_TPU_DEVICE_PLANE``): ``auto`` (default) enables
the plane exactly when a real accelerator backend is attached
(utils/device.host_regime() false); ``on`` forces it even on the CPU
backend (tests, the device-resident smoke); ``off`` disables it.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.ops import rs
from celestia_tpu.ops.gf256 import active_codec as _active_codec
from celestia_tpu.ops.gf256 import encode_matrix_bits
from celestia_tpu.utils import devprof, tracing
from celestia_tpu.utils.telemetry import clock as _clock

ENV_MODE = "CELESTIA_TPU_DEVICE_PLANE"

# One-way degradation pin, same ladder as utils/native.py: a device
# fault mid-run (tunnel loss, OOM, a gather that dies) poisons the plane
# for the REST OF THE PROCESS and every caller falls back to the byte-
# identical host legs.  Deliberately one-way — a chip that faulted once
# under load cannot silently come back, and a mid-chain flap between
# legs would make perf numbers unreadable.  Only clear_poison(force=True)
# (tests, operator intervention) clears it.
_poison_lock = threading.Lock()
_poison_reason: Optional[str] = None  # celint: guarded-by(_poison_lock)


def poison(reason: str) -> None:
    """Pin the device-resident plane OFF after a fault (loud, one-way).
    The in-flight block still commits identical roots: every fallback
    leg is byte-identical by construction."""
    global _poison_reason
    from celestia_tpu.utils import faults
    from celestia_tpu.utils.logging import Logger

    with _poison_lock:
        if _poison_reason is not None:
            return  # already degraded; first reason wins
        _poison_reason = reason
    faults.record_degradation("device_plane", reason)
    Logger(level="warn").warn(
        "device-resident DA plane poisoned: falling back to the host "
        "extend/serve paths for the rest of the process (byte-identical, "
        "more transfers)",
        reason=reason[:200],
    )


def poisoned() -> Optional[str]:
    """The poison reason, or None while the plane is trusted."""
    with _poison_lock:
        return _poison_reason


def clear_poison(force: bool = False) -> None:
    """Un-pin the degradation.  Refuses without ``force=True``: the pin
    exists precisely so nothing switches back silently."""
    global _poison_reason
    with _poison_lock:
        if _poison_reason is None:
            return
        if not force:
            raise RuntimeError(
                "the device-resident plane was poisoned "
                f"({_poison_reason!r}) and the degradation pin is one-way; "
                "pass force=True only if you KNOW the fault is resolved"
            )
        _poison_reason = None


def _mode() -> str:
    return os.environ.get(ENV_MODE, "auto").strip().lower()


def enabled() -> bool:
    """True when the device-resident extend/serve legs should run: the
    mode allows it (``on`` anywhere, ``auto`` only with a real
    accelerator backend) and the plane is not poisoned."""
    mode = _mode()
    if mode == "off":
        return False
    if poisoned() is not None:
        return False
    if mode == "on":
        return True
    from celestia_tpu.utils.device import host_regime

    return not host_regime()


@contextmanager
def forced(mode: str = "on"):
    """Temporarily pin the mode env (bench transfer-accounting legs, the
    device-resident smoke, tests) — restores the previous value even on
    error.  Process-global, like the env it sets."""
    prev = os.environ.get(ENV_MODE)
    os.environ[ENV_MODE] = mode
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(ENV_MODE, None)
        else:
            os.environ[ENV_MODE] = prev


@lru_cache(maxsize=1)
def _donate_input() -> bool:
    """Donate the square buffer on accelerator backends only: XLA cannot
    alias host CPU buffers and warns per compile there (see module docs)."""
    try:
        return str(jax.default_backend()) != "cpu"
    except Exception:
        return False


@lru_cache(maxsize=None)
def _extend_levels_fn(k: int, codec: str, donate: bool):
    """The fused device-resident program for square size k:

    square uint8[k,k,512] -> (eds uint8[2k,2k,512],
                              nmt levels tuple[(2, 2k, 2k>>j, 90)],
                              root levels tuple[(4k>>j, 32)])

    One XLA executable produces every downstream artifact of the
    proposal lifecycle — extension, all inner NMT nodes of the 4k axis
    trees (axis 0 of each level: 0=row trees, 1=column trees) and the
    complete RFC-6962 tree over the 4k roots (whose last level is the
    data root) — with zero host round trips between stages."""
    G = jnp.asarray(encode_matrix_bits(k, codec))

    def run(square: jnp.ndarray):
        eds = rs._extend(square, G)
        leaves = nmt_ops.eds_prefixed_leaves(eds)  # (2, 2k, 2k, 541)
        levels = nmt_ops.nmt_level_stack(leaves)
        roots = levels[-1][:, :, 0, :]  # (2, 2k, 90)
        all_roots = roots.reshape(4 * k, nmt_ops.NMT_DIGEST_SIZE)
        root_levels = nmt_ops.rfc6962_level_stack(all_roots)
        return eds, tuple(levels), tuple(root_levels)

    return jax.jit(run, donate_argnums=(0,) if donate else ())


class DevicePlaneEntry:
    """The device-buffer handle cached beside an eds_cache entry: the
    EDS shares, every NMT level and the root-tree levels, all still on
    their chip.  ``nbytes`` is computed from shapes — weighing an entry
    in the byte budget never forces a transfer."""

    __slots__ = ("k", "data_root", "eds", "levels", "root_levels", "nbytes")

    def __init__(self, k, data_root, eds, levels, root_levels):
        self.k = int(k)
        self.data_root = data_root
        self.eds = eds
        self.levels = tuple(levels)
        self.root_levels = tuple(root_levels)
        self.nbytes = int(
            int(eds.nbytes)
            + sum(int(a.nbytes) for a in self.levels)
            + sum(int(a.nbytes) for a in self.root_levels)
        )


def extend_and_header(square):
    """The device-resident twin of da/dah.extend_and_header: square
    uint8[k,k,512] -> (ExtendedDataSquare, DataAvailabilityHeader),
    byte-identical to the host pipeline (the consensus-safety
    requirement, pinned by tests/test_device_plane.py).

    D2H budget: 32 bytes (data root, eager) + 4k x 90 bytes (axis roots,
    one lazily-issued tuple fetch for the host DAH object).  The EDS and
    the level stacks stay on the device inside the returned
    :class:`DevicePlaneEntry`, registered in eds_cache's device-handle
    budget so process/commit and DAS serving find the block device-warm.
    """
    from celestia_tpu.da import eds_cache
    from celestia_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare

    k = int(square.shape[0])
    codec = _active_codec()
    with tracing.span("extend.device_plane", k=k, codec=codec):
        fn = _extend_levels_fn(k, codec, _donate_input())
        t0 = _clock()
        arr = jnp.asarray(square)
        # h2d charge: jnp.asarray ENQUEUES the upload — the recorded ms
        # is scheduling cost, the wire time overlaps the dispatch below
        devprof.record_transfer(
            "extend_levels", "h2d", k * k * 512, (_clock() - t0) * 1000.0
        )
        d = devprof.dispatch("extend_levels", k=k, codec=codec)
        eds_d, levels, root_levels = d.done(fn(arr))
        # the ONE eager hot-path D2H: the 32-byte data root
        data_root = bytes(devprof.fetch("data_root", root_levels[-1])[0])
        # axis roots, lazily fetched relative to the dispatch (they are
        # only needed to assemble the host DAH object) — ONE tuple fetch
        axis_roots = devprof.fetch("roots", levels[-1])  # (2, 2k, 1, 90)
        rr = axis_roots[0, :, 0, :]
        cc = axis_roots[1, :, 0, :]
        dah = DataAvailabilityHeader(
            tuple(rr[i].tobytes() for i in range(rr.shape[0])),
            tuple(cc[i].tobytes() for i in range(cc.shape[0])),
            data_root,
        )
    # cost accounting OUTSIDE the traced span (da/dah.py placement
    # contract); lower() reads avals only, so the donated arg is safe
    devprof.note_compile("extend_levels", fn, (arr,))
    entry = DevicePlaneEntry(k, data_root, eds_d, levels, root_levels)
    eds_cache.put_device_entry(data_root, entry)
    return ExtendedDataSquare(eds_d), dah


@lru_cache(maxsize=4096)
def _cell_node_indices(n: int, col: int, n_levels: int) -> tuple:
    """(level, index) of every sibling digest of the single-cell NMT
    range proof [col, col+1), in the EXACT traversal order
    da/proof.py nmt_range_proof_from_levels records them."""
    out: List[Tuple[int, int]] = []
    start, end = col, col + 1

    def walk(lo: int, hi: int, level: int) -> None:
        if lo >= end or hi <= start:
            out.append((level, lo >> level))
            return
        if hi - lo == 1:
            return
        mid = (lo + hi) // 2
        walk(lo, mid, level - 1)
        walk(mid, hi, level - 1)

    walk(0, n, n_levels - 1)
    return tuple(out)


def sample_proofs_batch(
    entry: DevicePlaneEntry, dah, coords: Sequence[Tuple[int, int]]
) -> list:
    """Serve n DAS proofs as pure gathers from the cached device level
    stacks: host integer arithmetic picks the proof-path indices, the
    digests and shares are gathered ON the device, and ONE batched
    ``device_get`` fetches everything — no re-hash, no row rebuild.
    Proofs are byte-identical to the host prover (coords order kept).

    Raises on any device fault — the caller (da/das.py) poisons the
    plane and falls back to the host prover for the same batch."""
    from celestia_tpu.da.das import SampleProof
    from celestia_tpu.da.proof import MerkleProof, NmtRangeProof

    k = entry.k
    n2 = 2 * k
    L = len(entry.levels)
    RL = len(entry.root_levels)
    total_roots = 4 * k
    # host-side index computation: per-level gather requests, filled per
    # coord in traversal order (the assembly below re-walks coords in
    # the same order, so per-level cursors reproduce the exact ordering)
    nmt_rows: List[List[int]] = [[] for _ in range(L)]
    nmt_idxs: List[List[int]] = [[] for _ in range(L)]
    for row, col in coords:
        for level, idx in _cell_node_indices(n2, col, L):
            nmt_rows[level].append(row)
            nmt_idxs[level].append(idx)
    root_idxs: List[List[int]] = [[] for _ in range(RL - 1)]
    for row, _col in coords:
        for j in range(RL - 1):
            root_idxs[j].append((row >> j) ^ 1)
    with tracing.span("das.device_gather", cells=len(coords), k=k):
        gathers = []
        used_levels = []
        for level in range(L):
            if not nmt_rows[level]:
                continue
            used_levels.append(level)
            r_a = jnp.asarray(nmt_rows[level], dtype=jnp.int32)
            i_a = jnp.asarray(nmt_idxs[level], dtype=jnp.int32)
            gathers.append(entry.levels[level][0, r_a, i_a])  # row trees
        for j in range(RL - 1):
            gathers.append(
                entry.root_levels[j][jnp.asarray(root_idxs[j], dtype=jnp.int32)]
            )
        rows_a = jnp.asarray([r for r, _ in coords], dtype=jnp.int32)
        cols_a = jnp.asarray([c for _, c in coords], dtype=jnp.int32)
        gathers.append(entry.eds[rows_a, cols_a])  # (n, 512) shares
        d = devprof.dispatch("das_proof_gather", cells=len(coords), k=k)
        gathered = d.done(tuple(gathers))
        # the proof path crosses in ONE batched fetch — the only D2H of
        # warm device-resident serving
        host = devprof.fetch("proof_gather", gathered)
    nmt_host = dict(zip(used_levels, host[: len(used_levels)]))
    root_host = host[len(used_levels) : len(used_levels) + (RL - 1)]
    shares_host = host[-1]
    cursors = [0] * L
    root_cursor = 0
    out = []
    for i, (row, col) in enumerate(coords):
        nodes = []
        for level, _idx in _cell_node_indices(n2, col, L):
            nodes.append(nmt_host[level][cursors[level]].tobytes())
            cursors[level] += 1
        aunts = tuple(
            root_host[j][root_cursor].tobytes() for j in range(RL - 1)
        )
        root_cursor += 1
        out.append(
            SampleProof(
                row=row,
                col=col,
                square_size=k,
                share=shares_host[i].tobytes(),
                nmt_proof=NmtRangeProof(col, col + 1, tuple(nodes)),
                row_root=dah.row_roots[row],
                root_proof=MerkleProof(
                    index=row, total=total_roots, aunts=aunts
                ),
            )
        )
    return out
