"""Blob, BlobTx envelope and IndexWrapper — the tx-side containers of blobs.

Behavioral parity with go-square/blob as used by the reference
(/root/reference/app/check_tx.go:19, x/blob/types/blob_tx.go).  The wire
formats here are this framework's own deterministic binary encodings (the
reference uses protobuf); the semantics match:

* ``BlobTx``     — envelope carrying a signed PayForBlobs tx plus its blobs;
                   this is what travels in the mempool and in block data.
* ``IndexWrapper`` — a PFB tx annotated with the share indexes where its blobs
                   start; this is what is written into the square's
                   PAY_FOR_BLOB namespace (app/encoding/index_wrapper_decoder.go).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from celestia_tpu.appconsts import DEFAULT_SHARE_VERSION, NAMESPACE_SIZE
from celestia_tpu.da.namespace import Namespace
from celestia_tpu.da.shares import _read_varint, _varint, sparse_shares_needed

_BLOB_TX_MAGIC = b"CTPUBLB0"
_INDEX_WRAPPER_MAGIC = b"CTPUIDX0"


@dataclass(frozen=True)
class Blob:
    namespace: Namespace
    data: bytes
    share_version: int = DEFAULT_SHARE_VERSION

    def shares_needed(self) -> int:
        return sparse_shares_needed(len(self.data))


@dataclass(frozen=True)
class BlobTx:
    """A signed PFB transaction together with the blobs it pays for."""

    tx: bytes
    blobs: Tuple[Blob, ...]

    def marshal(self) -> bytes:
        out = bytearray(_BLOB_TX_MAGIC)
        out += _varint(len(self.tx))
        out += self.tx
        out += _varint(len(self.blobs))
        for b in self.blobs:
            out += b.namespace.raw
            out += _varint(b.share_version)
            out += _varint(len(b.data))
            out += b.data
        return bytes(out)


def is_blob_tx(raw: bytes) -> bool:
    return raw.startswith(_BLOB_TX_MAGIC)


def unmarshal_blob_tx(raw: bytes) -> Optional[BlobTx]:
    """Parse a BlobTx envelope; None if ``raw`` is not one."""
    if not is_blob_tx(raw):
        return None
    pos = len(_BLOB_TX_MAGIC)
    try:
        tx_len, pos = _read_varint(raw, pos)
        tx = raw[pos : pos + tx_len]
        if len(tx) != tx_len:
            return None
        pos += tx_len
        n_blobs, pos = _read_varint(raw, pos)
        blobs: List[Blob] = []
        for _ in range(n_blobs):
            ns = Namespace(raw[pos : pos + NAMESPACE_SIZE])
            pos += NAMESPACE_SIZE
            sv, pos = _read_varint(raw, pos)
            dlen, pos = _read_varint(raw, pos)
            data = raw[pos : pos + dlen]
            if len(data) != dlen:
                return None
            pos += dlen
            blobs.append(Blob(ns, data, sv))
        if pos != len(raw):
            return None
        return BlobTx(tx, tuple(blobs))
    except (ValueError, IndexError):
        return None


@dataclass(frozen=True)
class IndexWrapper:
    """PFB tx + share indexes of its blobs, as laid out in the square."""

    tx: bytes
    share_indexes: Tuple[int, ...]

    def marshal(self) -> bytes:
        out = bytearray(_INDEX_WRAPPER_MAGIC)
        out += _varint(len(self.tx))
        out += self.tx
        out += _varint(len(self.share_indexes))
        for idx in self.share_indexes:
            out += int(idx).to_bytes(4, "big")
        return bytes(out)

    @staticmethod
    def marshalled_size(tx_len: int, n_blobs: int) -> int:
        """Size of the wrapper before indexes are known (indexes are fixed 4B)."""
        return (
            len(_INDEX_WRAPPER_MAGIC)
            + len(_varint(tx_len))
            + tx_len
            + len(_varint(n_blobs))
            + 4 * n_blobs
        )


def is_index_wrapper(raw: bytes) -> bool:
    return raw.startswith(_INDEX_WRAPPER_MAGIC)


def unmarshal_index_wrapper(raw: bytes) -> Optional[IndexWrapper]:
    if not is_index_wrapper(raw):
        return None
    pos = len(_INDEX_WRAPPER_MAGIC)
    try:
        tx_len, pos = _read_varint(raw, pos)
        tx = raw[pos : pos + tx_len]
        if len(tx) != tx_len:
            return None
        pos += tx_len
        n, pos = _read_varint(raw, pos)
        idxs = []
        for _ in range(n):
            idxs.append(int.from_bytes(raw[pos : pos + 4], "big"))
            pos += 4
        if pos != len(raw):
            return None
        return IndexWrapper(tx, tuple(idxs))
    except (ValueError, IndexError):
        return None
