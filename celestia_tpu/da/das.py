"""Data-availability sampling (DAS): the light-client protocol the EDS
exists for.

Role: sampling-based availability verification — the reference ecosystem's
light nodes sample random EDS cells with NMT proofs so *no single node
needs the full square* (SURVEY.md §5 "long-context analogue"; the
2x-extension guarantees any withheld original data forces >= 75% of cells
to be withheld, spec `specs/src/specs/data_structures.md`).  celestia-app
itself serves the data; the DAS client lives beside it the way
celestia-node's light client does — here both halves are native to this
framework:

  SampleProof   — one EDS cell + its row-NMT range proof + the row root's
                  membership proof in the data root.
  sample_proof  — prover (node side), serving any cell of the 2k x 2k EDS
                  (all four quadrants, with the Q0/parity namespace rule).
  LightClient   — verifier: samples coordinates uniformly with a local
                  seed, verifies every proof against the header's data
                  root, and reports the soundness bound
                  P[withheld block undetected] <= (3/4)^n.

Host hashing: one sample touches a single 2k-leaf tree; the per-level
device dispatches would cost more in launch latency than the ~2k SHA-256
calls cost on the host, so the prover hashes rows host-side (native C++
when available).

Serving plane (the vectorized path a production node fields millions of
light clients through):

  sample_proofs_batch — one request -> n cells.  Coordinates are grouped
      by row, each touched row's NMT level stack is built ONCE through
      the threaded host batch kernels (ops/sha256.sha256_batch_host —
      native SHA-NI via the hostpool, sharded hashlib otherwise), and
      one RFC-6962 level tree over the DAH's 4k axis roots serves every
      cell's root proof.  Emitted proofs are byte-identical to the
      per-cell prover (pinned by tests/test_das.py and the bench leg).
  das_rows cache — bounded LruCache (celint R2) of immutable row level
      stacks keyed ``(data_root, row)`` (plus the block's root tree at
      ``(data_root, "roots")``), layered on top of the EDS cache: a warm
      block answers ANY cell of a cached row with pure proof-path
      extraction.  Keys bind to the data root, so a stack cached for one
      block can never serve another; hit/miss telemetry rides the
      unified cache registry like every other cache.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from celestia_tpu.appconsts import NAMESPACE_SIZE, SHARE_SIZE
from celestia_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_tpu.da.namespace import PARITY_SHARE_NAMESPACE
from celestia_tpu.da.proof import (
    MerkleProof,
    NmtRangeProof,
    merkle_level_tree,
    merkle_proof,
    merkle_proof_from_levels,
    nmt_range_proof_from_levels,
)
from celestia_tpu.ops import nmt as nmt_ops
from celestia_tpu.utils.lru import LruCache


def _row_leaves(eds: ExtendedDataSquare, row: int) -> np.ndarray:
    """Namespace-prefixed NMT leaves of one EDS row (Q0 keeps own
    namespaces; every parity cell gets the parity namespace —
    pkg/wrapper's Push rule)."""
    k = eds.square_size
    cells = np.asarray(eds.shares[row])  # (2k, 512)
    n = 2 * k
    prefix = np.empty((n, NAMESPACE_SIZE), dtype=np.uint8)
    parity_ns = np.frombuffer(PARITY_SHARE_NAMESPACE.raw, dtype=np.uint8)
    if row < k:
        prefix[:k] = cells[:k, :NAMESPACE_SIZE]
        prefix[k:] = parity_ns
    else:
        prefix[:] = parity_ns
    return np.concatenate([prefix, cells], axis=1)


def _host_level_stack(leaves: np.ndarray) -> List[np.ndarray]:
    """NMT level stack of one small tree on the host (serial reference;
    the serving path uses :func:`_row_level_stacks_host`, pinned
    byte-identical to this by tests/test_das.py)."""
    digests = [
        nmt_ops.leaf_digest_np(leaves[i].tobytes()) for i in range(len(leaves))
    ]
    levels = [np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(-1, 90)]
    while len(digests) > 1:
        digests = [
            nmt_ops.combine_digests_np(digests[2 * i], digests[2 * i + 1])
            for i in range(len(digests) // 2)
        ]
        levels.append(
            np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(-1, 90)
        )
    return levels


_PARITY_NS = np.frombuffer(PARITY_SHARE_NAMESPACE.raw, dtype=np.uint8)


def _row_level_stacks_host(leaves: np.ndarray) -> List[List[np.ndarray]]:
    """Level stacks of R same-size NMTs: uint8[R, n, L] namespace-prefixed
    leaves -> R stacks of ``[(n, 90), (n/2, 90), ..., (1, 90)]``.

    The batched counterpart of :func:`_host_level_stack`: ONE
    ``sha256_batch_host`` dispatch per tree level across ALL rows
    (native SHA-NI on the hostpool when available) instead of
    rows x leaves scalar hashlib calls.  Byte-identical by construction
    — same leaf rule (ns || ns || sha256(0x00 || leaf)) and the same
    IgnoreMaxNamespace combine as ops/nmt.combine_digests_np.  Returned
    arrays are frozen (read-only): they are shared through the das_rows
    cache."""
    from celestia_tpu.ops.sha256 import sha256_batch_host

    R, n, L = leaves.shape
    ns = leaves[:, :, :NAMESPACE_SIZE]
    prefix = np.zeros((R, n, 1), dtype=np.uint8)
    h = sha256_batch_host(
        np.concatenate([prefix, leaves], axis=-1).reshape(R * n, L + 1)
    ).reshape(R, n, 32)
    levels = [np.concatenate([ns, ns, h], axis=-1)]
    while levels[-1].shape[1] > 1:
        cur = levels[-1]
        left, right = cur[:, 0::2], cur[:, 1::2]
        l_max = left[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        r_min = right[..., :NAMESPACE_SIZE]
        r_max = right[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        r_is_parity = np.all(r_min == _PARITY_NS, axis=-1, keepdims=True)
        max_ns = np.where(r_is_parity, l_max, r_max)
        one = np.ones(left.shape[:-1] + (1,), dtype=np.uint8)
        h = sha256_batch_host(
            np.concatenate([one, left, right], axis=-1).reshape(
                -1, 1 + 2 * nmt_ops.NMT_DIGEST_SIZE
            )
        ).reshape(left.shape[:-1] + (32,))
        levels.append(
            np.concatenate([left[..., :NAMESPACE_SIZE], max_ns, h], axis=-1)
        )
    stacks: List[List[np.ndarray]] = []
    for r in range(R):
        stack = []
        for lv in levels:
            a = np.ascontiguousarray(lv[r])
            a.flags.writeable = False
            stack.append(a)
        stacks.append(stack)
    return stacks


# ---------------------------------------------------------------------------
# das_rows: the bounded proof/row cache (serving plane, ROADMAP #4)
# ---------------------------------------------------------------------------

# Keys: (data_root, row) -> that row's frozen NMT level stack;
#        (data_root, "roots") -> the block's RFC-6962 level tree over the
#        4k axis roots.  Binding every key to the data root means a warm
#        entry can NEVER serve a different block — a wrong root is a
#        plain miss, recomputed honestly (adversarial tests pin this).
# A k=128 row stack is ~46 KiB (2 x 256 x 90 B of digests), so the
# default byte budget (~32 MiB) holds several hundred hot rows across a
# handful of recent blocks on top of the EDS cache's squares.
_ROWS_MAX_ENTRIES = int(os.environ.get("CELESTIA_TPU_DAS_ROWS", "8192"))
_ROWS_MAX_BYTES = int(
    float(os.environ.get("CELESTIA_TPU_DAS_ROWS_MB", "32")) * 1024 * 1024
)


def _levels_weigher(key, value) -> int:
    try:
        return sum(int(lv.nbytes) for lv in value) + 64
    except Exception:
        return 64


_ROWS_CACHE = LruCache(
    "das_rows",
    _ROWS_MAX_ENTRIES,
    weigher=_levels_weigher,
    max_bytes=_ROWS_MAX_BYTES,
)


def rows_cache() -> LruCache:
    """The process-global das_rows cache (content keyed: sharing across
    App instances is safe for the same reason the EDS cache is)."""
    return _ROWS_CACHE


@dataclass(frozen=True)
class SampleProof:
    """One sampled EDS cell, provable to the block's data root."""

    row: int
    col: int
    square_size: int  # original k
    share: bytes  # 512-byte cell
    nmt_proof: NmtRangeProof  # within the row's NMT
    row_root: bytes
    root_proof: MerkleProof  # row root -> data root

    def leaf(self) -> bytes:
        """The ns-prefixed NMT leaf this cell hashes to."""
        k = self.square_size
        if self.row < k and self.col < k:
            prefix = self.share[:NAMESPACE_SIZE]
        else:
            prefix = PARITY_SHARE_NAMESPACE.raw
        return prefix + self.share

    def verify(self, data_root: bytes) -> bool:
        k = self.square_size
        if not (0 <= self.row < 2 * k and 0 <= self.col < 2 * k):
            return False
        if len(self.share) != SHARE_SIZE:
            return False
        if self.nmt_proof.start != self.col or self.nmt_proof.end != self.col + 1:
            return False
        if not self.nmt_proof.verify(self.row_root, [self.leaf()], 2 * k):
            return False
        # the row root's position among the DAH's 4k roots is its row index
        if self.root_proof.index != self.row or self.root_proof.total != 4 * k:
            return False
        return self.root_proof.verify(data_root, self.row_root)

    def to_dict(self) -> dict:
        return {
            "row": self.row,
            "col": self.col,
            "square_size": self.square_size,
            "share": self.share.hex(),
            "nmt": {
                "start": self.nmt_proof.start,
                "end": self.nmt_proof.end,
                "nodes": [n.hex() for n in self.nmt_proof.nodes],
            },
            "row_root": self.row_root.hex(),
            "root": {
                "index": self.root_proof.index,
                "total": self.root_proof.total,
                "aunts": [a.hex() for a in self.root_proof.aunts],
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SampleProof":
        return cls(
            row=int(d["row"]),
            col=int(d["col"]),
            square_size=int(d["square_size"]),
            share=bytes.fromhex(d["share"]),
            nmt_proof=NmtRangeProof(
                int(d["nmt"]["start"]),
                int(d["nmt"]["end"]),
                tuple(bytes.fromhex(n) for n in d["nmt"]["nodes"]),
            ),
            row_root=bytes.fromhex(d["row_root"]),
            root_proof=MerkleProof(
                index=int(d["root"]["index"]),
                total=int(d["root"]["total"]),
                aunts=tuple(bytes.fromhex(a) for a in d["root"]["aunts"]),
            ),
        )


def _sample_proof_uncached(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    row: int,
    col: int,
) -> SampleProof:
    """The original per-cell prover: rebuilds the row's full level stack
    and the 4k-root list on EVERY call, touching no cache.  Kept as the
    byte-identity reference for the batch path (tests + the bench leg's
    per-sample baseline); production callers use :func:`sample_proof` /
    :func:`sample_proofs_batch`."""
    k = eds.square_size
    if not (0 <= row < 2 * k and 0 <= col < 2 * k):
        raise ValueError(f"sample ({row}, {col}) outside the {2*k}x{2*k} EDS")
    levels = _host_level_stack(_row_leaves(eds, row))
    nmt_proof = nmt_range_proof_from_levels(levels, col, col + 1)
    all_roots = list(dah.row_roots) + list(dah.col_roots)
    return SampleProof(
        row=row,
        col=col,
        square_size=k,
        share=np.asarray(eds.shares[row, col]).tobytes(),
        nmt_proof=nmt_proof,
        row_root=dah.row_roots[row],
        root_proof=merkle_proof(all_roots, row),
    )


def sample_proof(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    row: int,
    col: int,
) -> SampleProof:
    """Prove one EDS cell (any quadrant) to the data root.

    Internally a 1-cell :func:`sample_proofs_batch`: the single-cell RPC
    path shares the das_rows cache, so a warm row answers with pure
    proof-path extraction and the 4k-root merkle tree is built once per
    block instead of once per call."""
    return sample_proofs_batch(eds, dah, [(row, col)])[0]


def sample_proofs_batch(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    coords: Sequence[Tuple[int, int]],
) -> List[SampleProof]:
    """Prove n EDS cells in one pass (proofs returned in ``coords``
    order, each byte-identical to the per-cell prover's output).

    Coordinates are grouped by row; every touched row's level stack is
    built ONCE through the batched host kernels and cached under
    ``(data_root, row)``, and one cached RFC-6962 level tree over the
    DAH's 4k axis roots serves every root proof — n samples of a warm
    block cost n proof-path extractions, not n full row passes."""
    k = eds.square_size
    n2 = 2 * k
    coords = [(int(r), int(c)) for r, c in coords]
    for row, col in coords:
        if not (0 <= row < n2 and 0 <= col < n2):
            raise ValueError(
                f"sample ({row}, {col}) outside the {n2}x{n2} EDS"
            )
    if not coords:
        return []
    data_root = dah.hash
    # device-resident serving (da/device_plane.py): if this block's
    # level stacks are still on their chip (the proposer's own block at
    # process/commit time, or any block extended through the plane), a
    # proof is an index computation plus ONE batched device_get of the
    # proof paths — no row rebuild, no re-hash.  Byte-identical to the
    # host prover below (pinned by tests/test_device_plane.py); any
    # device fault poisons the plane one-way and THIS batch falls
    # through to the host path.
    from celestia_tpu.da import device_plane, eds_cache

    if device_plane.enabled():
        dev_entry = eds_cache.get_device_entry(data_root)
        if dev_entry is not None and dev_entry.k == k:
            try:
                return device_plane.sample_proofs_batch(
                    dev_entry, dah, coords
                )
            except Exception as e:
                device_plane.poison(f"device proof gather failed: {e!r}")
    all_roots = list(dah.row_roots) + list(dah.col_roots)
    total = len(all_roots)
    # root-proof material: one balanced level tree per block (4k is a
    # power of two whenever k is; anything else falls back to the
    # per-call prover's tree walk)
    root_levels = None
    if total and not (total & (total - 1)):
        root_levels = _ROWS_CACHE.get((data_root, "roots"))
        if root_levels is None:
            root_levels = merkle_level_tree(all_roots)
            _ROWS_CACHE.put((data_root, "roots"), root_levels)
    rows_needed = sorted({r for r, _ in coords})
    cached = _ROWS_CACHE.get_many([(data_root, r) for r in rows_needed])
    stacks = {
        r: s for r, s in zip(rows_needed, cached) if s is not None
    }
    missing = [r for r in rows_needed if r not in stacks]
    if missing:
        built = _row_level_stacks_host(
            np.stack([_row_leaves(eds, r) for r in missing])
        )
        _ROWS_CACHE.put_many(
            ((data_root, r), s) for r, s in zip(missing, built)
        )
        stacks.update(zip(missing, built))
    shares = eds.shares
    out: List[SampleProof] = []
    for row, col in coords:
        nmt_proof = nmt_range_proof_from_levels(stacks[row], col, col + 1)
        root_proof = (
            merkle_proof_from_levels(root_levels, row)
            if root_levels is not None
            else merkle_proof(all_roots, row)
        )
        out.append(
            SampleProof(
                row=row,
                col=col,
                square_size=k,
                share=np.asarray(shares[row, col]).tobytes(),
                nmt_proof=nmt_proof,
                row_root=dah.row_roots[row],
                root_proof=root_proof,
            )
        )
    return out


@dataclass
class SampleResult:
    coordinates: List[Tuple[int, int]]
    verified: int
    failed: List[Tuple[int, int, str]]  # (row, col, reason)

    @property
    def available(self) -> bool:
        return not self.failed

    @property
    def confidence(self) -> float:
        """P[an unavailable block would have escaped detection] is at most
        (3/4)^n: recovering a withheld share requires withholding > 25% of
        the EDS (k+1 of 2k cells in some axis), so each uniformly-sampled
        cell is withheld with probability > 1/4."""
        return 1.0 - 0.75 ** self.verified


class LightClient:
    """DAS verifier: trusts only a header (data root + square size)."""

    def __init__(self, data_root: bytes, square_size: int, seed: int = 0):
        self.data_root = data_root
        self.k = square_size
        # celint: allow(consensus-determinism) — explicitly seeded sampling
        # RNG: cell choice is a client-local probabilistic check whose
        # draws never reach consensus bytes, and the seed keeps it
        # reproducible in tests
        self._rng = np.random.default_rng(seed)

    def pick_coordinates(self, n: int) -> List[Tuple[int, int]]:
        n_axis = 2 * self.k
        flat = self._rng.choice(n_axis * n_axis, size=min(n, n_axis * n_axis),
                                replace=False)
        return [(int(f) // n_axis, int(f) % n_axis) for f in flat]

    def sample(
        self,
        fetch: Optional[Callable[[int, int], Optional[SampleProof]]] = None,
        n_samples: int = 16,
        *,
        fetch_batch: Optional[
            Callable[[List[Tuple[int, int]]], Iterable[Optional[SampleProof]]]
        ] = None,
    ) -> SampleResult:
        """Fetch + verify n uniformly-random cells.  A None response, a
        proof for the wrong coordinate, or a proof that fails verification
        all count as withheld — a provider must PROVE every sampled cell.

        ``fetch_batch`` routes the whole draw through the vectorized
        serving plane (ONE request for all n cells — the DasSampleBatch
        RPC); it receives the coordinate list and returns proofs (or
        None) positionally.  A short response leaves the tail cells
        "not served" — a provider cannot shrink the sample."""
        if (fetch is None) == (fetch_batch is None):
            raise ValueError("exactly one of fetch/fetch_batch is required")
        coords = self.pick_coordinates(n_samples)
        if fetch_batch is not None:
            proofs = list(fetch_batch(list(coords)))
            proofs += [None] * (len(coords) - len(proofs))
        else:
            proofs = [fetch(row, col) for row, col in coords]
        verified = 0
        failed: List[Tuple[int, int, str]] = []
        for (row, col), proof in zip(coords, proofs):
            if proof is None:
                failed.append((row, col, "not served"))
                continue
            if (proof.row, proof.col) != (row, col):
                failed.append((row, col, "proof for the wrong coordinate"))
                continue
            if proof.square_size != self.k:
                failed.append((row, col, "square size mismatch"))
                continue
            if not proof.verify(self.data_root):
                failed.append((row, col, "proof does not verify"))
                continue
            verified += 1
        return SampleResult(coords, verified, failed)
