"""Data-availability sampling (DAS): the light-client protocol the EDS
exists for.

Role: sampling-based availability verification — the reference ecosystem's
light nodes sample random EDS cells with NMT proofs so *no single node
needs the full square* (SURVEY.md §5 "long-context analogue"; the
2x-extension guarantees any withheld original data forces >= 75% of cells
to be withheld, spec `specs/src/specs/data_structures.md`).  celestia-app
itself serves the data; the DAS client lives beside it the way
celestia-node's light client does — here both halves are native to this
framework:

  SampleProof   — one EDS cell + its row-NMT range proof + the row root's
                  membership proof in the data root.
  sample_proof  — prover (node side), serving any cell of the 2k x 2k EDS
                  (all four quadrants, with the Q0/parity namespace rule).
  LightClient   — verifier: samples coordinates uniformly with a local
                  seed, verifies every proof against the header's data
                  root, and reports the soundness bound
                  P[withheld block undetected] <= (3/4)^n.

Host hashing: one sample touches a single 2k-leaf tree; the per-level
device dispatches would cost more in launch latency than the ~2k SHA-256
calls cost on the host, so the prover hashes rows host-side (native C++
when available).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from celestia_tpu.appconsts import NAMESPACE_SIZE, SHARE_SIZE
from celestia_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_tpu.da.namespace import PARITY_SHARE_NAMESPACE
from celestia_tpu.da.proof import (
    MerkleProof,
    NmtRangeProof,
    merkle_proof,
    nmt_range_proof_from_levels,
)
from celestia_tpu.ops import nmt as nmt_ops


def _row_leaves(eds: ExtendedDataSquare, row: int) -> np.ndarray:
    """Namespace-prefixed NMT leaves of one EDS row (Q0 keeps own
    namespaces; every parity cell gets the parity namespace —
    pkg/wrapper's Push rule)."""
    k = eds.square_size
    cells = np.asarray(eds.shares[row])  # (2k, 512)
    n = 2 * k
    prefix = np.empty((n, NAMESPACE_SIZE), dtype=np.uint8)
    parity_ns = np.frombuffer(PARITY_SHARE_NAMESPACE.raw, dtype=np.uint8)
    if row < k:
        prefix[:k] = cells[:k, :NAMESPACE_SIZE]
        prefix[k:] = parity_ns
    else:
        prefix[:] = parity_ns
    return np.concatenate([prefix, cells], axis=1)


def _host_level_stack(leaves: np.ndarray) -> List[np.ndarray]:
    """NMT level stack of one small tree on the host."""
    digests = [
        nmt_ops.leaf_digest_np(leaves[i].tobytes()) for i in range(len(leaves))
    ]
    levels = [np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(-1, 90)]
    while len(digests) > 1:
        digests = [
            nmt_ops.combine_digests_np(digests[2 * i], digests[2 * i + 1])
            for i in range(len(digests) // 2)
        ]
        levels.append(
            np.frombuffer(b"".join(digests), dtype=np.uint8).reshape(-1, 90)
        )
    return levels


@dataclass(frozen=True)
class SampleProof:
    """One sampled EDS cell, provable to the block's data root."""

    row: int
    col: int
    square_size: int  # original k
    share: bytes  # 512-byte cell
    nmt_proof: NmtRangeProof  # within the row's NMT
    row_root: bytes
    root_proof: MerkleProof  # row root -> data root

    def leaf(self) -> bytes:
        """The ns-prefixed NMT leaf this cell hashes to."""
        k = self.square_size
        if self.row < k and self.col < k:
            prefix = self.share[:NAMESPACE_SIZE]
        else:
            prefix = PARITY_SHARE_NAMESPACE.raw
        return prefix + self.share

    def verify(self, data_root: bytes) -> bool:
        k = self.square_size
        if not (0 <= self.row < 2 * k and 0 <= self.col < 2 * k):
            return False
        if len(self.share) != SHARE_SIZE:
            return False
        if self.nmt_proof.start != self.col or self.nmt_proof.end != self.col + 1:
            return False
        if not self.nmt_proof.verify(self.row_root, [self.leaf()], 2 * k):
            return False
        # the row root's position among the DAH's 4k roots is its row index
        if self.root_proof.index != self.row or self.root_proof.total != 4 * k:
            return False
        return self.root_proof.verify(data_root, self.row_root)

    def to_dict(self) -> dict:
        return {
            "row": self.row,
            "col": self.col,
            "square_size": self.square_size,
            "share": self.share.hex(),
            "nmt": {
                "start": self.nmt_proof.start,
                "end": self.nmt_proof.end,
                "nodes": [n.hex() for n in self.nmt_proof.nodes],
            },
            "row_root": self.row_root.hex(),
            "root": {
                "index": self.root_proof.index,
                "total": self.root_proof.total,
                "aunts": [a.hex() for a in self.root_proof.aunts],
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "SampleProof":
        return cls(
            row=int(d["row"]),
            col=int(d["col"]),
            square_size=int(d["square_size"]),
            share=bytes.fromhex(d["share"]),
            nmt_proof=NmtRangeProof(
                int(d["nmt"]["start"]),
                int(d["nmt"]["end"]),
                tuple(bytes.fromhex(n) for n in d["nmt"]["nodes"]),
            ),
            row_root=bytes.fromhex(d["row_root"]),
            root_proof=MerkleProof(
                index=int(d["root"]["index"]),
                total=int(d["root"]["total"]),
                aunts=tuple(bytes.fromhex(a) for a in d["root"]["aunts"]),
            ),
        )


def sample_proof(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    row: int,
    col: int,
) -> SampleProof:
    """Prove one EDS cell (any quadrant) to the data root."""
    k = eds.square_size
    if not (0 <= row < 2 * k and 0 <= col < 2 * k):
        raise ValueError(f"sample ({row}, {col}) outside the {2*k}x{2*k} EDS")
    levels = _host_level_stack(_row_leaves(eds, row))
    nmt_proof = nmt_range_proof_from_levels(levels, col, col + 1)
    all_roots = list(dah.row_roots) + list(dah.col_roots)
    return SampleProof(
        row=row,
        col=col,
        square_size=k,
        share=np.asarray(eds.shares[row, col]).tobytes(),
        nmt_proof=nmt_proof,
        row_root=dah.row_roots[row],
        root_proof=merkle_proof(all_roots, row),
    )


@dataclass
class SampleResult:
    coordinates: List[Tuple[int, int]]
    verified: int
    failed: List[Tuple[int, int, str]]  # (row, col, reason)

    @property
    def available(self) -> bool:
        return not self.failed

    @property
    def confidence(self) -> float:
        """P[an unavailable block would have escaped detection] is at most
        (3/4)^n: recovering a withheld share requires withholding > 25% of
        the EDS (k+1 of 2k cells in some axis), so each uniformly-sampled
        cell is withheld with probability > 1/4."""
        return 1.0 - 0.75 ** self.verified


class LightClient:
    """DAS verifier: trusts only a header (data root + square size)."""

    def __init__(self, data_root: bytes, square_size: int, seed: int = 0):
        self.data_root = data_root
        self.k = square_size
        # celint: allow(consensus-determinism) — explicitly seeded sampling
        # RNG: cell choice is a client-local probabilistic check whose
        # draws never reach consensus bytes, and the seed keeps it
        # reproducible in tests
        self._rng = np.random.default_rng(seed)

    def pick_coordinates(self, n: int) -> List[Tuple[int, int]]:
        n_axis = 2 * self.k
        flat = self._rng.choice(n_axis * n_axis, size=min(n, n_axis * n_axis),
                                replace=False)
        return [(int(f) // n_axis, int(f) % n_axis) for f in flat]

    def sample(
        self,
        fetch: Callable[[int, int], Optional[SampleProof]],
        n_samples: int = 16,
    ) -> SampleResult:
        """Fetch + verify n uniformly-random cells.  A None response, a
        proof for the wrong coordinate, or a proof that fails verification
        all count as withheld — a provider must PROVE every sampled cell."""
        coords = self.pick_coordinates(n_samples)
        verified = 0
        failed: List[Tuple[int, int, str]] = []
        for row, col in coords:
            proof = fetch(row, col)
            if proof is None:
                failed.append((row, col, "not served"))
                continue
            if (proof.row, proof.col) != (row, col):
                failed.append((row, col, "proof for the wrong coordinate"))
                continue
            if proof.square_size != self.k:
                failed.append((row, col, "square size mismatch"))
                continue
            if not proof.verify(self.data_root):
                failed.append((row, col, "proof does not verify"))
                continue
            verified += 1
        return SampleResult(coords, verified, failed)
