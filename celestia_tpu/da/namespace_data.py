"""Namespace-scoped share retrieval with trustless completeness.

Role: the `GetSharesByNamespace` API the reference ecosystem's light nodes
use to pull a rollup's data — every share of one namespace, provable both
for INCLUSION (NMT range proofs to the committed row roots) and
COMPLETENESS (the NMT's ordered-namespace property: sibling nodes outside
the returned range carry min/max namespaces that exclude the target, and
rows whose roots exclude the namespace need no proof at all).

The verifier needs only a DAH it has checked against a trusted data root
(`DataAvailabilityHeader.hash`); no share outside the namespace is ever
transferred.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from celestia_tpu.appconsts import NAMESPACE_SIZE, SHARE_SIZE
from celestia_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_tpu.da.das import _host_level_stack, _row_leaves
from celestia_tpu.da.namespace import PARITY_SHARE_NAMESPACE
from celestia_tpu.da.proof import NmtRangeProof, nmt_range_proof_from_levels
from celestia_tpu.ops import nmt as nmt_ops

PARITY_NS = PARITY_SHARE_NAMESPACE.raw


def root_namespace_range(root: bytes) -> Tuple[bytes, bytes]:
    """(min, max) namespace of a 90-byte NMT root digest."""
    return root[:NAMESPACE_SIZE], root[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]




@dataclass(frozen=True)
class RowNamespaceData:
    row: int
    start: int  # column range within the row's 2k leaves
    end: int
    shares: Tuple[bytes, ...]
    proof: NmtRangeProof
    # absence witness: when the row's root COVERS the namespace but no
    # share carries it, this is the ns-prefixed leaf at `start` whose
    # namespace is the first one above the target (shares empty, end ==
    # start + 1).  Valid blocks have namespace-ordered rows (ProcessProposal
    # rejects unordered squares), so one witness + left-sibling bounds
    # prove the gap — the nmt library's AbsenceProof shape.
    absence_leaf: bytes = b""


@dataclass(frozen=True)
class NamespaceData:
    """All shares of one namespace in a block, with proofs."""

    namespace: bytes
    square_size: int  # original k
    rows: Tuple[RowNamespaceData, ...]

    def blobs_payload(self) -> bytes:
        """The raw concatenated shares (callers parse sequences out of
        them with da.shares.parse_sparse_shares)."""
        return b"".join(s for r in self.rows for s in r.shares)

    def verify(self, dah: DataAvailabilityHeader) -> bool:
        """Verify inclusion AND completeness against a trusted DAH.

        Every row whose root's namespace range covers the target MUST be
        present with a complete range proof; rows whose roots exclude it
        need nothing (their absence is proven by the root itself)."""
        ns = self.namespace
        k = self.square_size
        if len(dah.row_roots) != 2 * k:
            return False
        by_row = {r.row: r for r in self.rows}
        if len(by_row) != len(self.rows):
            return False  # duplicate rows
        # every entry must name a real row — an out-of-range row would be
        # skipped by the root loop below and its shares would flow into
        # blobs_payload() unverified
        if any(not 0 <= r.row < 2 * k for r in self.rows):
            return False
        # rows must come in row order: payload bytes concatenate in tuple
        # order, so a permuted (but individually valid) response would
        # scramble the reassembled blobs
        if list(by_row) != sorted(by_row):
            return False
        for row_idx, root in enumerate(dah.row_roots):
            ns_min, ns_max = root_namespace_range(root)
            covers = ns_min <= ns <= ns_max
            entry = by_row.get(row_idx)
            if not covers:
                if entry is not None:
                    return False  # claimed data in a row that excludes it
                continue
            if entry is None:
                return False  # withheld a row the DAH proves may hold the ns
            if entry.start != entry.proof.start or entry.end != entry.proof.end:
                return False
            if not entry.shares:
                # absence: a single-leaf witness above the namespace, with
                # every left sibling bounded below it
                if entry.end != entry.start + 1 or not entry.absence_leaf:
                    return False
                if entry.absence_leaf[:NAMESPACE_SIZE] <= ns:
                    return False
                if not entry.proof.verify(
                    root, [entry.absence_leaf], 2 * k
                ):
                    return False
                # right siblings are unconstrained for absence (namespace
                # ordering + one above-target witness already close the gap)
                if not entry.proof.sibling_namespace_bounds(
                    2 * k, ns, check_right=False
                ):
                    return False
                continue
            if len(entry.shares) != entry.end - entry.start:
                return False
            if any(len(s) != SHARE_SIZE for s in entry.shares):
                return False
            leaves = [ns + s for s in entry.shares]
            if not entry.proof.verify_complete_namespace(
                root, leaves, 2 * k, ns
            ):
                return False
        return True

    def to_dict(self) -> dict:
        return {
            "namespace": self.namespace.hex(),
            "square_size": self.square_size,
            "rows": [
                {
                    "row": r.row,
                    "start": r.start,
                    "end": r.end,
                    "shares": [s.hex() for s in r.shares],
                    "nodes": [n.hex() for n in r.proof.nodes],
                    "absence_leaf": r.absence_leaf.hex(),
                }
                for r in self.rows
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NamespaceData":
        return cls(
            namespace=bytes.fromhex(d["namespace"]),
            square_size=int(d["square_size"]),
            rows=tuple(
                RowNamespaceData(
                    row=int(r["row"]),
                    start=int(r["start"]),
                    end=int(r["end"]),
                    shares=tuple(bytes.fromhex(s) for s in r["shares"]),
                    proof=NmtRangeProof(
                        int(r["start"]), int(r["end"]),
                        tuple(bytes.fromhex(n) for n in r["nodes"]),
                    ),
                    absence_leaf=bytes.fromhex(r.get("absence_leaf", "")),
                )
                for r in d["rows"]
            ),
        )


def _level_stacks_for_rows(
    eds: ExtendedDataSquare, row_idxs: List[int]
) -> List[List[np.ndarray]]:
    """Level stacks for the given rows.  A handful of rows hash on the
    host (device launch latency would dominate); wide requests go through
    the batched device kernel in log2(2k) dispatches total — the same
    trade new_share_inclusion_proof makes."""
    if len(row_idxs) <= 4:
        return [
            _host_level_stack(_row_leaves(eds, r)) for r in row_idxs
        ]
    import jax

    leaves = np.stack([_row_leaves(eds, r) for r in row_idxs])  # (R, 2k, L)
    batched = [
        np.asarray(lv) for lv in nmt_ops.nmt_level_stack(jax.numpy.asarray(leaves))
    ]
    return [[lv[i] for lv in batched] for i in range(len(row_idxs))]


def get_shares_by_namespace(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    namespace: bytes,
) -> NamespaceData:
    """Prover: collect every share of ``namespace`` with row-wise complete
    range proofs.  Rows whose committed roots exclude the namespace are
    skipped — the roots themselves prove the absence."""
    if len(namespace) != NAMESPACE_SIZE:
        raise ValueError(f"namespace must be {NAMESPACE_SIZE} bytes")
    if namespace >= PARITY_NS:
        raise ValueError("the parity namespace is not queryable data")
    k = eds.square_size
    # phase 1: classify covered rows (present range vs absence witness)
    plan: List[Tuple[int, int, int, bool]] = []  # (row, start, end, absent)
    for row_idx in range(2 * k):
        ns_min, ns_max = root_namespace_range(dah.row_roots[row_idx])
        if not (ns_min <= namespace <= ns_max):
            continue
        cells = np.asarray(eds.shares[row_idx])
        # namespaced data lives in Q0 (parity cells carry the parity ns);
        # shares of one namespace are contiguous within a row (square
        # layout orders namespaces)
        cols = [
            c for c in range(k)
            if row_idx < k and cells[c, :NAMESPACE_SIZE].tobytes() == namespace
        ]
        if not cols:
            # root covers the ns but the row holds none of it: absence
            # witness = the first leaf whose namespace exceeds the target
            witness = next(
                (
                    c for c in range(k)
                    if cells[c, :NAMESPACE_SIZE].tobytes() > namespace
                ),
                k,  # everything below target: first parity cell witnesses
            )
            plan.append((row_idx, witness, witness + 1, True))
            continue
        start, end = cols[0], cols[-1] + 1
        if cols != list(range(start, end)):
            raise ValueError(
                f"namespace {namespace.hex()} not contiguous in row {row_idx}"
            )
        plan.append((row_idx, start, end, False))
    # phase 2: one (possibly batched) level-stack pass over covered rows
    stacks = _level_stacks_for_rows(eds, [p[0] for p in plan])
    rows: List[RowNamespaceData] = []
    for (row_idx, start, end, absent), levels in zip(plan, stacks):
        proof = nmt_range_proof_from_levels(levels, start, end)
        cells = np.asarray(eds.shares[row_idx])
        if absent:
            witness = start
            leaf_prefix = (
                cells[witness, :NAMESPACE_SIZE].tobytes()
                if witness < k
                else PARITY_NS
            )
            rows.append(
                RowNamespaceData(
                    row=row_idx, start=start, end=end, shares=(),
                    proof=proof,
                    absence_leaf=leaf_prefix + cells[witness].tobytes(),
                )
            )
        else:
            rows.append(
                RowNamespaceData(
                    row=row_idx, start=start, end=end,
                    shares=tuple(
                        cells[c].tobytes() for c in range(start, end)
                    ),
                    proof=proof,
                )
            )
    return NamespaceData(namespace=namespace, square_size=k, rows=tuple(rows))
