"""Tx client: thread-safe signer with sequence tracking and recovery.

Parity with /root/reference/pkg/user/signer.go: local-vs-network sequence
tracking (:31-55), SubmitTx / SubmitPayForBlob (:146-169), broadcast with
nonce-mismatch recovery and re-signing (:268-309), ConfirmTx polling
(:365-395), gas estimation (:397-420), and tx options (tx_options.go).

``node`` is any object exposing the node surface (celestia_tpu/node):
  broadcast_tx(raw) -> TxResult-like (code, log, hash)
  get_tx(tx_hash) -> Optional[confirmation dict]
  account_info(address) -> (account_number, sequence)
  simulate(raw) -> gas estimate
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

from celestia_tpu.client import errors as client_errors
from celestia_tpu.da.blob import Blob, BlobTx
from celestia_tpu.da.inclusion import create_commitment
from celestia_tpu.state.modules.blob import estimate_gas
# SubmitResult moved to state/tx.py (celint R8: the node tier produces
# it); re-exported here so client-side callers are unchanged
from celestia_tpu.state.tx import (  # noqa: F401
    Fee,
    Msg,
    MsgPayForBlobs,
    SubmitResult,
    Tx,
)
from celestia_tpu.utils.secp256k1 import PrivateKey

DEFAULT_GAS_LIMIT = 210_000
DEFAULT_POLL_INTERVAL_S = 0.05
DEFAULT_CONFIRM_TIMEOUT_S = 30.0


class Signer:
    """Thread-safe account signer bound to one node connection."""

    def __init__(
        self,
        node,
        private_key: PrivateKey,
        chain_id: Optional[str] = None,
        gas_price: float = 0.002,
    ):
        self.node = node
        self.key = private_key
        self.pubkey = private_key.public_key()
        self.address = self.pubkey.address()
        self.chain_id = chain_id or node.chain_id
        self.gas_price = gas_price
        # RLock held across the whole sign -> broadcast -> increment window
        # so concurrent submitters never sign with the same sequence
        # (signer.go holds its mutex across broadcastTx the same way)
        self._lock = threading.RLock()
        acct_num, seq = node.account_info(self.address)
        self.account_number = acct_num
        self._sequence = seq

    # --- fees -------------------------------------------------------------

    def _fee(self, gas_limit: int, gas_price: Optional[float] = None) -> Fee:
        price = self.gas_price if gas_price is None else gas_price
        amount = int(gas_limit * price + 0.999999)
        return Fee(amount=amount, gas_limit=gas_limit)

    def estimate_gas(self, msgs: Sequence[Msg]) -> int:
        """Simulate-based estimation (signer.go:397-420)."""
        tx = Tx(
            tuple(msgs), self._fee(DEFAULT_GAS_LIMIT), self.pubkey.compressed(),
            self._sequence, self.account_number,
        )
        return self.node.simulate(tx.marshal())

    # --- submission -------------------------------------------------------

    def sign_tx(
        self,
        msgs: Sequence[Msg],
        gas_limit: int = DEFAULT_GAS_LIMIT,
        gas_price: Optional[float] = None,
        memo: str = "",
        sequence: Optional[int] = None,
        timeout_height: int = 0,
        fee_granter: bytes = b"",
    ) -> Tx:
        with self._lock:
            seq = self._sequence if sequence is None else sequence
            tx = Tx(
                tuple(msgs), self._fee(gas_limit, gas_price),
                self.pubkey.compressed(), seq, self.account_number, memo,
                timeout_height=timeout_height, fee_granter=fee_granter,
            )
            return tx.signed(self.key, self.chain_id)

    def _broadcast(self, make_raw, max_retries: int = 3) -> SubmitResult:
        """Broadcast with nonce-mismatch recovery (signer.go:268-309): on an
        'incorrect account sequence' rejection, adopt the node's expected
        sequence and re-sign.  The lock spans sign+broadcast+increment so a
        concurrent submitter cannot reuse the sequence."""
        with self._lock:
            for _ in range(max_retries):
                raw = make_raw()
                res = self.node.broadcast_tx(raw)
                if res.code == 0:
                    self._sequence += 1
                    return res
                if client_errors.is_nonce_mismatch(res.log):
                    expected = client_errors.parse_expected_sequence(res.log)
                    if expected is not None:
                        self._sequence = expected
                        continue
                return res
            return res

    def submit_tx(self, msgs: Sequence[Msg], **opts) -> SubmitResult:
        """Sign, broadcast, confirm (signer.go SubmitTx)."""
        res = self._broadcast(lambda: self.sign_tx(msgs, **opts).marshal())
        if res.code != 0:
            return res
        return self.confirm_tx(res.tx_hash)

    def submit_pay_for_blob(
        self,
        blobs: Sequence[Blob],
        gas_limit: Optional[int] = None,
        **opts,
    ) -> SubmitResult:
        """SubmitPayForBlob (signer.go:162-169): build MsgPayForBlobs with
        share commitments, wrap the signed tx + blobs in a BlobTx envelope."""
        blobs = list(blobs)
        msg = MsgPayForBlobs(
            signer=self.address,
            namespaces=tuple(b.namespace.raw for b in blobs),
            blob_sizes=tuple(len(b.data) for b in blobs),
            share_commitments=tuple(create_commitment(b) for b in blobs),
            share_versions=tuple(b.share_version for b in blobs),
        )
        if gas_limit is None:
            gas_limit = estimate_gas([len(b.data) for b in blobs])

        def make_raw() -> bytes:
            tx = self.sign_tx([msg], gas_limit=gas_limit, **opts)
            return BlobTx(tx=tx.marshal(), blobs=tuple(blobs)).marshal()

        res = self._broadcast(make_raw)
        if res.code != 0:
            return res
        return self.confirm_tx(res.tx_hash)

    # --- confirmation -----------------------------------------------------

    def confirm_tx(
        self,
        tx_hash: bytes,
        timeout_s: float = DEFAULT_CONFIRM_TIMEOUT_S,
        poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
    ) -> SubmitResult:
        """Poll until the tx lands in a block (signer.go:365-395), on the
        unified RetryPolicy (utils/faults.py): jittered poll intervals,
        hard deadline budget, reproducible under a chaos seed."""
        from celestia_tpu.utils.faults import RetryPolicy

        info = RetryPolicy(
            base_s=poll_interval_s,
            cap_s=max(poll_interval_s * 2, 0.25),
            deadline_s=timeout_s,
        ).poll(
            lambda: self.node.get_tx(tx_hash),
            what=f"tx {tx_hash.hex()} confirmation",
        )
        return SubmitResult(
            code=info["code"], log=info.get("log", ""),
            tx_hash=tx_hash, height=info["height"],
        )

    @property
    def sequence(self) -> int:
        with self._lock:
            return self._sequence
