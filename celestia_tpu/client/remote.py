"""Client-tier re-export of the gRPC RemoteNode.

The implementation moved to node/remote.py (celint R8): the mesh is its
heaviest user — gossip links, catch-up pulls and state-sync fetches are
a node acting as an RPC client — and node/ may not import client/.
The wallet/CLI tier keeps importing it from here; this shim is the
whole public surface.
"""

from celestia_tpu.node.remote import (  # noqa: F401
    RPC_TELEMETRY,
    RemoteError,
    RemoteNode,
    SERVICE,
    client_rpc_exposition,
)
