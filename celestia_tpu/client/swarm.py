"""DAS light-client swarm harness: crowd-shaped load for the serving plane.

Extends client/txsim.py's pattern (deterministic per-actor rng, cloneable
workloads, one driver loop) from transactions to DATA-AVAILABILITY
SAMPLING: hundreds-to-thousands of simulated light clients with
zipf-distributed block/row interest, generation churn, mixed batch
sizes, and a configurable fraction of HOSTILE over-askers, driving a
live node over the real gRPC boundary (RemoteNode.das_sample_batch with
a client-asserted ``peer`` identity, so the server's per-peer QoS
accounting sees the crowd).

The report answers the questions the ROADMAP poses about planet-scale
serving: p50/p99 request latency per expected tier (``light`` = honest
population, ``hostile`` = the over-askers), client-observed shed rate,
cells/s, and the Jain fairness index over per-client served counts —
the client-side mirror of the numbers the server exposes per peer.
Everything is seeded (``SwarmConfig.seed``); wall-clock concurrency
makes shed *counts* load-dependent, so consumers assert on bounds and
distributions, never exact schedules.
"""

from __future__ import annotations

import threading
from concurrent import futures
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from celestia_tpu.utils import faults
from celestia_tpu.utils.telemetry import clock, jain_fairness_index


@dataclass
class SwarmConfig:
    """Shape of the crowd.  ``clients`` includes ``hostile`` over-askers
    (the first ``hostile`` indexes), who ask ``hostile_multiplier`` x the
    honest batch size every round.  ``churn`` replaces that fraction of
    the HONEST population with fresh identities between rounds (new
    generation suffix — the server sees genuinely new peers).
    ``deadline_s`` is a hard wall budget: the driver stops issuing new
    rounds once it is exceeded and reports ``deadline_hit`` instead of
    running forever (the bench leg's never-a-dead-round contract)."""

    clients: int = 64
    hostile: int = 8
    rounds: int = 3
    samples_per_round: int = 6
    hostile_multiplier: int = 8
    zipf_a: float = 1.3
    churn: float = 0.1
    batch_sizes: Tuple[int, ...] = (4, 8, 16)
    seed: int = 0
    workers: int = 8
    retry_attempts: int = 4
    request_deadline_s: float = 5.0
    deadline_s: float = 60.0


class SwarmClient:
    """One simulated light client: deterministic rng (txsim's
    ``seed * 1000 + i`` convention, widened with the churn generation),
    zipf block/row interest, and a stable asserted peer identity."""

    def __init__(
        self,
        index: int,
        generation: int,
        hostile: bool,
        blocks: List[Tuple[int, int]],
        cfg: SwarmConfig,
    ):
        self.index = index
        self.hostile = hostile
        tag = "hostile" if hostile else "swarm"
        self.peer_id = f"{tag}-g{generation}-{index:04d}"
        self.blocks = blocks
        self.cfg = cfg
        self.rng = np.random.default_rng(
            cfg.seed * 1000 + generation * 1_000_003 + index
        )

    def _zipf_index(self, n: int) -> int:
        # zipf rank (1-based, unbounded tail) clamped into [0, n): the
        # head blocks/rows soak most of the interest, like real crowds
        return min(int(self.rng.zipf(self.cfg.zipf_a)) - 1, n - 1)

    def pick_batch(self) -> Tuple[int, List[Tuple[int, int]]]:
        """(height, coords) for one sampling round — hostile clients
        over-ask by ``hostile_multiplier``."""
        height, k = self.blocks[self._zipf_index(len(self.blocks))]
        want = int(self.rng.choice(list(self.cfg.batch_sizes)))
        want *= self.cfg.samples_per_round
        if self.hostile:
            want *= self.cfg.hostile_multiplier
        side = 2 * k
        coords = []
        for _ in range(want):
            r = self._zipf_index(side)
            c = int(self.rng.integers(0, side))
            coords.append((r, c))
        return height, coords


def _percentiles(samples_ms: List[float]) -> Dict[str, float]:
    if not samples_ms:
        return {"count": 0, "p50_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0}
    arr = np.asarray(samples_ms, dtype=np.float64)
    return {
        "count": int(arr.size),
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "max_ms": round(float(arr.max()), 3),
    }


def run_swarm(
    address: str,
    blocks: List[Tuple[int, int]],
    cfg: Optional[SwarmConfig] = None,
) -> dict:
    """Drive a live node at ``address`` with the configured crowd.

    ``blocks`` is the sampleable universe: ``(height, square_size)``
    pairs (square_size = the ORIGINAL k; coordinates span the extended
    2k x 2k square).  Returns the swarm report described in the module
    docstring.  Client-side failures are per-request, never fatal — a
    saturated node yields a high shed rate, not an exception."""
    from celestia_tpu.node.remote import RemoteNode

    cfg = cfg or SwarmConfig()
    if not blocks:
        raise ValueError("swarm needs at least one sampleable block")
    n_hostile = min(cfg.hostile, cfg.clients)
    population = [
        SwarmClient(i, 0, i < n_hostile, blocks, cfg)
        for i in range(cfg.clients)
    ]

    lock = threading.Lock()
    lat_ms: Dict[str, List[float]] = {"light": [], "hostile": []}
    served_by_peer: Dict[str, int] = {}
    totals = {"requests": 0, "failed": 0, "asked": 0, "served": 0}
    groups = {
        "light": {"requests": 0, "failed": 0, "served": 0},
        "hostile": {"requests": 0, "failed": 0, "served": 0},
    }
    remotes: List[RemoteNode] = []
    tls = threading.local()

    def _remote() -> RemoteNode:
        r = getattr(tls, "remote", None)
        if r is None:
            r = RemoteNode(address, timeout_s=cfg.request_deadline_s * 2)
            tls.remote = r
            with lock:
                remotes.append(r)
        return r

    def client_round(cl: SwarmClient) -> None:
        height, coords = cl.pick_batch()
        group = "hostile" if cl.hostile else "light"
        policy = faults.RetryPolicy(
            attempts=cfg.retry_attempts, base_s=0.01, cap_s=0.05,
            deadline_s=cfg.request_deadline_s,
            seed=cfg.seed * 7919 + cl.index,
        )
        t0 = clock()
        served = 0
        failed = 0
        try:
            out = _remote().das_sample_batch(
                height, coords, peer=cl.peer_id, policy=policy
            )
            served = len(out["proofs"])
        except Exception as e:
            # a shed-to-exhaustion (faults.Overloaded) or transport
            # hiccup is DATA for the swarm — the request failed, the
            # crowd marches on; noted, never silently dropped
            faults.note("swarm.request", e)
            failed = 1
        ms = (clock() - t0) * 1000.0
        with lock:
            lat_ms[group].append(ms)
            totals["requests"] += 1
            totals["failed"] += failed
            totals["asked"] += len(coords)
            totals["served"] += served
            groups[group]["requests"] += 1
            groups[group]["failed"] += failed
            groups[group]["served"] += served
            served_by_peer[cl.peer_id] = (
                served_by_peer.get(cl.peer_id, 0) + served
            )

    t_start = clock()
    rounds_run = 0
    deadline_hit = False
    try:
        with futures.ThreadPoolExecutor(
            max_workers=max(1, cfg.workers)
        ) as pool:
            for rnd in range(cfg.rounds):
                if clock() - t_start > cfg.deadline_s:
                    deadline_hit = True
                    break
                list(pool.map(client_round, population))
                rounds_run += 1
                # churn: a slice of the honest population leaves and is
                # replaced by fresh identities (next generation)
                n_churn = int(cfg.churn * (cfg.clients - n_hostile))
                for j in range(n_churn):
                    idx = n_hostile + (
                        (rnd * n_churn + j) % max(1, cfg.clients - n_hostile)
                    )
                    population[idx] = SwarmClient(
                        idx, rnd + 1, False, blocks, cfg
                    )
    finally:
        for r in remotes:
            try:
                r.close()
            except Exception as e:
                faults.note("swarm.close", e)

    elapsed_s = max(1e-9, clock() - t_start)
    return {
        "clients": cfg.clients,
        "hostile": n_hostile,
        "rounds_run": rounds_run,
        "requests": totals["requests"],
        "failed": totals["failed"],
        "cells_asked": totals["asked"],
        "cells_served": totals["served"],
        "samples_per_s": round(totals["served"] / elapsed_s, 3),
        "shed_rate": round(
            totals["failed"] / max(1, totals["requests"]), 4
        ),
        "fairness_index": jain_fairness_index(served_by_peer.values()),
        "groups": {
            name: dict(
                st,
                shed_rate=round(st["failed"] / max(1, st["requests"]), 4),
            )
            for name, st in groups.items()
        },
        "latency": {
            group: _percentiles(samples)
            for group, samples in lat_ms.items()
        },
        "elapsed_s": round(elapsed_s, 3),
        "deadline_hit": deadline_hit,
    }
