"""Client-side Blobstream verification (VERDICT r3 #5).

Parity with /root/reference/x/blobstream/client/verify.go:197,323
(VerifyShares / VerifyDataRootInclusion): prove that shares committed at
some height are covered by a Blobstream DataCommitment attestation — the
artifact an EVM rollup bridge consumes — walking three links, each
verified CLIENT-SIDE against nothing but the attestation root:

1. share inclusion -> the block's data root (NMT range proof + row-root
   merkle proof, da/proof.ShareInclusionProof);
2. the height's DataCommitment window (queried from the node);
3. the (height, data_root) tuple's merkle inclusion in the window's
   data_root_tuple_root (RFC-6962 proof, da/proof.MerkleProof).

Trust model (stated precisely): the DataCommitment attestation — and
with it the data_root_tuple_root — is the TRUST ANCHOR and is taken as
served.  In the reference deployment that root lives in the Blobstream
EVM contract, placed there under the bridge valset's signatures; here
the node's attested root plays that role (anchor it independently —
e.g. prove the attestation record against a BFT-certified app hash via
store/proof — when the serving node itself is untrusted).  Everything
BELOW the anchor is verified client-side: a tampered share, share
proof, data root, window claim, or tuple proof fails the corresponding
check no matter what the node serves.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from celestia_tpu.da.proof import MerkleProof, ShareInclusionProof


class BlobstreamVerifyError(ValueError):
    pass


def verify_data_root_inclusion(
    height: int, data_root: bytes, proof: dict, tuple_root: bytes
) -> bool:
    """VerifyDataRootInclusion parity (client/verify.go:323): check the
    (height, data_root) tuple leaf against the attested tuple root."""
    leaf = height.to_bytes(8, "big") + data_root
    mp = MerkleProof(
        index=int(proof["index"]),
        total=int(proof["total"]),
        aunts=tuple(bytes.fromhex(a) for a in proof["aunts"]),
    )
    return mp.verify(tuple_root, leaf)


@dataclass(frozen=True)
class VerifiedShares:
    height: int
    data_root: bytes
    nonce: int
    begin_block: int
    end_block: int
    tuple_root: bytes


def verify_shares(
    node, height: int, start: int, end: int
) -> VerifiedShares:
    """VerifyShares parity (client/verify.go:197): prove shares
    [start, end) at ``height`` are committed to by a Blobstream
    DataCommitment.  ``node`` is anything with the abci_query surface
    (RemoteNode or TestNode).  Raises BlobstreamVerifyError on any
    broken link; returns the verified chain's facts on success."""
    # 1. share -> data root
    bundle = node.abci_query(
        "custom/proof/share", {"height": height, "start": start, "end": end}
    )
    proof = ShareInclusionProof.from_dict(bundle["proof"])
    data_root = bytes.fromhex(bundle["data_root"])
    if not proof.verify(data_root):
        raise BlobstreamVerifyError(
            "share inclusion proof does not verify against the data root"
        )
    # 2. which DataCommitment window covers this height?
    rng = node.abci_query(
        "custom/blobstream/data_commitment_range", {"height": height}
    )
    if not rng.get("found"):
        raise BlobstreamVerifyError(
            f"no DataCommitment attestation covers height {height} "
            "(window not yet closed?)"
        )
    att = rng["data_commitment"]
    tuple_root = bytes.fromhex(att["data_root_tuple_root"])
    # 3. (height, data_root) -> the attested tuple root
    dri = node.abci_query(
        "custom/blobstream/data_root_inclusion",
        {
            "height": height,
            "begin": att["begin_block"],
            "end": att["end_block"],
        },
    )
    served_root = bytes.fromhex(dri["data_root"])
    if served_root != data_root:
        raise BlobstreamVerifyError(
            "node served a different data root for the tuple proof than "
            "the share proof was verified against"
        )
    if not verify_data_root_inclusion(height, data_root, dri, tuple_root):
        raise BlobstreamVerifyError(
            "data root tuple proof does not verify against the attested "
            "DataCommitment root"
        )
    return VerifiedShares(
        height=height,
        data_root=data_root,
        nonce=int(att["nonce"]),
        begin_block=int(att["begin_block"]),
        end_block=int(att["end_block"]),
        tuple_root=tuple_root,
    )
