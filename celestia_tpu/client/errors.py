"""Client-side error parsing for recoverable tx failures.

Parity with /root/reference/app/errors/: ParseExpectedSequence
(nonce_mismatch.go:34 — extract the expected sequence so the signer can
re-sign) and ParseInsufficientMinGasPrice (insufficient_gas_price.go:23 —
compute the fee that would have been accepted).
"""

from __future__ import annotations

import re
from typing import Optional

_SEQUENCE_RE = re.compile(
    r"account sequence mismatch, expected (\d+), got (\d+)"
)
_MIN_FEE_RE = re.compile(
    r"insufficient fee.*?: got (\d+)utia, required (\d+)utia"
)


def is_nonce_mismatch(log: str) -> bool:
    return "incorrect account sequence" in log or _SEQUENCE_RE.search(log) is not None


def parse_expected_sequence(log: str) -> Optional[int]:
    m = _SEQUENCE_RE.search(log)
    return int(m.group(1)) if m else None


def is_insufficient_min_gas_price(log: str) -> bool:
    return "insufficient fee" in log


def parse_required_fee(log: str) -> Optional[int]:
    m = _MIN_FEE_RE.search(log)
    return int(m.group(2)) if m else None
