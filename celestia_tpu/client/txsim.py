"""txsim: composable transaction load generator.

Parity with /root/reference/test/txsim/: the Sequence interface
(sequence.go:16-31) with cloneable blob/send/stake sequences (blob.go:23,
send.go:23, stake.go:19) and the run loop (run.go:31-115) that drives N
sequences against a node, each with its own funded signer.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence as TypingSequence

import numpy as np

from celestia_tpu.client.signer import Signer
from celestia_tpu.da.blob import Blob
from celestia_tpu.da.namespace import Namespace
from celestia_tpu.state.tx import MsgDelegate, MsgSend
from celestia_tpu.utils.secp256k1 import PrivateKey


class Sequence:
    """One repeating workload (sequence.go Sequence interface)."""

    def clone(self, n: int) -> List["Sequence"]:
        import copy

        return [copy.deepcopy(self) for _ in range(n)]

    def init(self, signer: Signer, rng: np.random.Generator) -> None:
        self.signer = signer
        self.rng = rng

    def next(self) -> Optional[dict]:
        """Submit one tx; return a result record (None = sequence done)."""
        raise NotImplementedError


@dataclass
class BlobSequence(Sequence):
    """Random blobs within size/count bounds (txsim/blob.go)."""

    size_min: int = 100
    size_max: int = 10_000
    blobs_per_tx: int = 1
    namespace_seed: bytes = b"txsim"

    def next(self) -> Optional[dict]:
        blobs = []
        for i in range(self.blobs_per_tx):
            size = int(self.rng.integers(self.size_min, self.size_max + 1))
            ns = Namespace.v0(
                hashlib.sha256(self.namespace_seed + bytes([i])).digest()[:10]
            )
            data = self.rng.integers(0, 256, size, dtype=np.uint8).tobytes()
            blobs.append(Blob(ns, data))
        res = self.signer.submit_pay_for_blob(blobs)
        return {"type": "blob", "code": res.code, "log": res.log, "height": res.height}


@dataclass
class SendSequence(Sequence):
    """Token transfers to a rotating set of destinations (txsim/send.go)."""

    amount: int = 100

    def next(self) -> Optional[dict]:
        dest = hashlib.sha256(self.rng.bytes(8)).digest()[:20]
        res = self.signer.submit_tx([MsgSend(self.signer.address, dest, self.amount)])
        return {"type": "send", "code": res.code, "log": res.log, "height": res.height}


@dataclass
class StakeSequence(Sequence):
    """Delegations to the validator set (txsim/stake.go)."""

    amount: int = 1_000_000

    def next(self) -> Optional[dict]:
        # transport-agnostic: the validators query route works both
        # in-process and over gRPC (RemoteNode.abci_query)
        validators = self.signer.node.abci_query("custom/staking/validators", {})
        if not validators:
            return None
        val = validators[int(self.rng.integers(len(validators)))]
        res = self.signer.submit_tx(
            [MsgDelegate(self.signer.address, bytes.fromhex(val["operator"]), self.amount)]
        )
        return {"type": "stake", "code": res.code, "log": res.log, "height": res.height}


def _drive(
    sequences: TypingSequence[Sequence],
    signers: List[Signer],
    iterations: int,
    seed: int,
) -> List[dict]:
    """The round-robin drive loop shared by run/run_remote (run.go:31-115;
    the reference runs each sequence in a goroutine — here rounds
    interleave deterministically, which exercises the same mempool /
    sequence contention paths reproducibly)."""
    results: List[dict] = []
    for i, seq in enumerate(sequences):
        seq.init(signers[i], np.random.default_rng(seed * 1000 + i))
    active = list(sequences)
    for _ in range(iterations):
        still_active = []
        for seq in active:
            rec = seq.next()
            if rec is None:  # sequence finished: stop polling it
                continue
            results.append(rec)
            still_active.append(seq)
        active = still_active
        if not active:
            break
    return results


def run_remote(
    node,
    master_signer: "Signer",
    sequences: TypingSequence[Sequence],
    iterations: int = 10,
    seed: int = 0,
    funding: int = 10**9,
) -> List[dict]:
    """txsim against a REMOTE node (test/cmd/txsim/cli.go parity): the
    master key funds one derived sub-account per sequence over the network
    (the reference's master-account funding flow), then sequences run
    round-robin."""
    keys = [
        PrivateKey.from_seed(b"txsim-sub-%d" % i + seed.to_bytes(4, "big"))
        for i in range(len(sequences))
    ]
    # one multi-msg tx funds every sub-account: a single broadcast +
    # confirmation instead of N round trips
    res = master_signer.submit_tx(
        [
            MsgSend(master_signer.address, key.public_key().address(), funding)
            for key in keys
        ]
    )
    if res.code != 0:
        raise RuntimeError(f"funding sub-accounts failed: {res.log}")
    signers = [Signer(node, key) for key in keys]
    return _drive(sequences, signers, iterations, seed)


def run(
    node,
    sequences: TypingSequence[Sequence],
    iterations: int = 10,
    seed: int = 0,
    funding: int = 10**12,
) -> List[dict]:
    """txsim against an in-process node: sub-accounts are funded straight
    from the faucet (minted), then sequences run round-robin."""
    signers = []
    for i in range(len(sequences)):
        key = PrivateKey.from_seed(b"txsim-%d" % i + seed.to_bytes(4, "big"))
        addr = key.public_key().address()
        node.app.bank.mint(addr, funding)
        node.app.accounts.get_or_create(addr)
        signers.append(Signer(node, key))
    return _drive(sequences, signers, iterations, seed)
