"""celestia_tpu package root.

Kept import-light: one environment check arms the lock-order shadow
checker (utils/lockwatch.py) BEFORE any submodule constructs its
module-level locks — the watcher can only wrap locks whose construction
it precedes.  Without ``CELESTIA_TPU_LOCKWATCH`` in the environment this
file does nothing.
"""

import os as _os

if _os.environ.get("CELESTIA_TPU_LOCKWATCH", "").strip():
    from celestia_tpu.utils import lockwatch as _lockwatch

    _lockwatch.install_from_env()
