"""Protocol constants for the TPU-native Celestia-style DA framework.

Behavioral parity with the reference constants in
/root/reference/pkg/appconsts/global_consts.go:29-92,
initial_consts.go:8-31, versioned_consts.go:19-34, v1/app_consts.go,
v2/app_consts.go, consensus_consts.go:5-12.  These values define the share
layout, square bounds, gas model and consensus timing envelope; they cannot
change within a network's lifetime (except the versioned ones, dispatched on
app version).
"""

from __future__ import annotations

# --- Namespace layout (global_consts.go:17-27) ---
NAMESPACE_VERSION_SIZE = 1
NAMESPACE_ID_SIZE = 28
NAMESPACE_SIZE = NAMESPACE_VERSION_SIZE + NAMESPACE_ID_SIZE  # 29
NAMESPACE_VERSION_MAX = 255

# Raw bytes of the parity-share namespace (version 0xFF, id all-0xFF —
# global_consts.go:68-75).  da/namespace.py wraps these in its Namespace
# type; the bytes themselves live HERE because ops/nmt.py prefixes every
# Q1-Q3 leaf with them and ops/ sits below da/ in the package DAG
# (celint R8: ops may not import da).
PARITY_SHARE_NAMESPACE_RAW = b"\xff" * NAMESPACE_SIZE

# --- Share layout (global_consts.go:29-66) ---
SHARE_SIZE = 512
SHARE_INFO_BYTES = 1
SEQUENCE_LEN_BYTES = 4
SHARE_VERSION_ZERO = 0
DEFAULT_SHARE_VERSION = SHARE_VERSION_ZERO
MAX_SHARE_VERSION = 127
COMPACT_SHARE_RESERVED_BYTES = 4

FIRST_COMPACT_SHARE_CONTENT_SIZE = (
    SHARE_SIZE
    - NAMESPACE_SIZE
    - SHARE_INFO_BYTES
    - SEQUENCE_LEN_BYTES
    - COMPACT_SHARE_RESERVED_BYTES
)  # 474
CONTINUATION_COMPACT_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - COMPACT_SHARE_RESERVED_BYTES
)  # 478
FIRST_SPARSE_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - SEQUENCE_LEN_BYTES
)  # 478
CONTINUATION_SPARSE_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES
)  # 482

MIN_SQUARE_SIZE = 1
MIN_SHARE_COUNT = MIN_SQUARE_SIZE * MIN_SQUARE_SIZE

SUPPORTED_SHARE_VERSIONS = (SHARE_VERSION_ZERO,)

BOND_DENOM = "utia"

# --- Hashes ---
HASH_LENGTH = 32  # SHA-256

# --- App versions (versioned_consts.go, v1/, v2/) ---
V1_VERSION = 1
V2_VERSION = 2
LATEST_VERSION = V2_VERSION


def subtree_root_threshold(_app_version: int = LATEST_VERSION) -> int:
    """Target upper bound on subtree roots per share commitment (ADR-013).

    versioned_consts.go:19-27 — constant 64 for all current versions.
    """
    return 64


def square_size_upper_bound(_app_version: int = LATEST_VERSION) -> int:
    """Hard cap on the effective square size (versioned_consts.go:26-34)."""
    return 128


DEFAULT_SUBTREE_ROOT_THRESHOLD = subtree_root_threshold()
DEFAULT_SQUARE_SIZE_UPPER_BOUND = square_size_upper_bound()

# --- Governance-modifiable initial params (initial_consts.go:8-31) ---
DEFAULT_GOV_MAX_SQUARE_SIZE = 64
DEFAULT_MAX_BYTES = (
    DEFAULT_GOV_MAX_SQUARE_SIZE
    * DEFAULT_GOV_MAX_SQUARE_SIZE
    * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
)
DEFAULT_GAS_PER_BLOB_BYTE = 8
DEFAULT_MIN_GAS_PRICE = 0.002  # utia
DEFAULT_UNBONDING_TIME_SECONDS = 3 * 7 * 24 * 3600

# v2 global min gas price enforced by x/minfee (v2/app_consts.go:5-9).
# Stored and compared as an integer in utia-per-gas parts-per-million:
# consensus-critical fee math must never touch floats (same rationale as the
# mint module's integer fixed point).  2000 ppm == 0.002 utia/gas.
GLOBAL_MIN_GAS_PRICE_PPM = 2000

# --- Consensus timing (consensus_consts.go:5-12) ---
TIMEOUT_PROPOSE_SECONDS = 10
TIMEOUT_COMMIT_SECONDS = 11
GOAL_BLOCK_TIME_SECONDS = 15

# --- Blobstream (celestia-core consts.DataCommitmentBlocksLimit) ---
DATA_COMMITMENT_BLOCKS_LIMIT = 1000


def round_up_power_of_two(n: int) -> int:
    """Smallest power of two >= n (n >= 0; 0 -> 1)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def round_down_power_of_two(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    if n < 1:
        raise ValueError(f"round_down_power_of_two requires n >= 1, got {n}")
    return 1 << (n.bit_length() - 1)


def is_power_of_two(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0
