"""Namespaced Merkle Tree as a batched, level-synchronous device reduction.

TPU-native equivalent of celestiaorg/nmt as used by the reference's
``pkg/wrapper.ErasuredNamespacedMerkleTree`` (nmt_wrapper.go:26-114) and the
hasher specified in test/util/malicious/hasher.go:1-71 (the de-validated copy
that documents the exact digest format):

* leaf digest  = ns || ns || sha256(0x00 || ns || data)
* node digest  = minNs || maxNs || sha256(0x01 || left || right)
  with minNs = left.min and, because IgnoreMaxNamespace=true, maxNs =
  left.max when right.min == 0xFF..FF (an all-parity right subtree), else
  right.max.
* empty root   = zeros(29) || zeros(29) || sha256("")

Digests are 29+29+32 = 90 bytes.  Instead of per-leaf Push calls into one
tree object at a time, all 4k axis trees of the extended square reduce
together: one leaf-hash batch of shape [n_axes, n_leaves] then log2(n_leaves)
pairwise-combine levels — each level one fused sha256 batch.

The leaf prefix rule mirrors nmt_wrapper.go:93-114: Q0 cells are prefixed
with their own namespace, every cell outside Q0 with the parity namespace.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from celestia_tpu.appconsts import (
    NAMESPACE_SIZE,
    PARITY_SHARE_NAMESPACE_RAW,
    SHARE_SIZE,
)
from celestia_tpu.ops.sha256 import sha256

NMT_DIGEST_SIZE = 2 * NAMESPACE_SIZE + 32  # 90

_PARITY_NS = np.frombuffer(PARITY_SHARE_NAMESPACE_RAW, dtype=np.uint8)


def leaf_digests(leaves: jnp.ndarray) -> jnp.ndarray:
    """Hash namespaced leaves: uint8[..., L] -> uint8[..., 90].

    ``leaves`` already carry their namespace prefix (ns || data).
    """
    ns = leaves[..., :NAMESPACE_SIZE]
    prefix = jnp.zeros(leaves.shape[:-1] + (1,), dtype=jnp.uint8)  # 0x00
    h = sha256(jnp.concatenate([prefix, leaves], axis=-1))
    return jnp.concatenate([ns, ns, h], axis=-1)


def combine_level(nodes: jnp.ndarray) -> jnp.ndarray:
    """One reduction level: uint8[..., m, 90] -> uint8[..., m//2, 90]."""
    left = nodes[..., 0::2, :]
    right = nodes[..., 1::2, :]
    l_min = left[..., :NAMESPACE_SIZE]
    l_max = left[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
    r_min = right[..., :NAMESPACE_SIZE]
    r_max = right[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
    parity = jnp.asarray(_PARITY_NS)
    r_is_parity = jnp.all(r_min == parity, axis=-1, keepdims=True)  # IgnoreMaxNamespace
    max_ns = jnp.where(r_is_parity, l_max, r_max)
    prefix = jnp.ones(left.shape[:-1] + (1,), dtype=jnp.uint8)  # 0x01
    h = sha256(jnp.concatenate([prefix, left, right], axis=-1))
    return jnp.concatenate([l_min, max_ns, h], axis=-1)


def nmt_roots(leaves: jnp.ndarray) -> jnp.ndarray:
    """Full NMT reduction: uint8[..., n, L] namespaced leaves -> uint8[..., 90].

    n must be a power of two (EDS axes always are).
    """
    n = leaves.shape[-2]
    if n & (n - 1):
        raise ValueError(f"leaf count must be a power of two, got {n}")
    nodes = leaf_digests(leaves)
    while nodes.shape[-2] > 1:
        nodes = combine_level(nodes)
    return nodes[..., 0, :]


def eds_prefixed_leaves(eds: jnp.ndarray) -> jnp.ndarray:
    """Build the namespace-prefixed leaves of all row and column trees.

    eds: uint8[2k, 2k, SHARE_SIZE] -> uint8[2, 2k, 2k, 29+SHARE_SIZE]
    (axis 0: 0=row trees, 1=column trees; leaves ordered along each axis).

    Prefix = the share's own namespace inside Q0, the parity namespace
    everywhere else (nmt_wrapper.go:93-114).
    """
    n2 = eds.shape[0]
    k = n2 // 2
    own_ns = eds[..., :NAMESPACE_SIZE]  # (2k, 2k, 29)
    parity = jnp.broadcast_to(jnp.asarray(_PARITY_NS), own_ns.shape)
    r = jnp.arange(n2)
    in_q0 = (r[:, None] < k) & (r[None, :] < k)  # (2k, 2k)
    prefix = jnp.where(in_q0[..., None], own_ns, parity)
    rows = jnp.concatenate([prefix, eds], axis=-1)  # (2k rows, 2k leaves, 541)
    cols = rows.transpose(1, 0, 2)  # column trees
    return jnp.stack([rows, cols], axis=0)


def eds_nmt_roots(eds: jnp.ndarray) -> jnp.ndarray:
    """All 4k NMT axis roots of an EDS: uint8[2k,2k,512] -> uint8[2, 2k, 90]."""
    return nmt_roots(eds_prefixed_leaves(eds))


# one jitted whole-EDS root program shared by every eager caller; the
# race on first assignment is benign (two identical jit wrappers, one
# survives, the XLA executable cache is shared anyway)
_EDS_ROOTS_JIT = None


def eds_nmt_roots_device(eds) -> np.ndarray:
    """Jitted, devprof-instrumented DEVICE entry for the whole-EDS root
    pass: uint8[2k,2k,B] (host or device) -> uint8[2, 2k, 90] on the
    host.  The eager :func:`eds_nmt_roots` stays the traceable form for
    fused callers; this wrapper is the standalone dispatch
    (da/dah.new_data_availability_header's jax leg), bracketed with
    device timing + XLA cost accounting (utils/devprof.py)."""
    global _EDS_ROOTS_JIT
    from celestia_tpu.utils import devprof

    if _EDS_ROOTS_JIT is None:
        _EDS_ROOTS_JIT = jax.jit(eds_nmt_roots)
    arr = jnp.asarray(eds)
    d = devprof.dispatch("eds_nmt_roots", n2=int(arr.shape[0]))
    out = d.done(_EDS_ROOTS_JIT(arr))
    devprof.note_compile("eds_nmt_roots", _EDS_ROOTS_JIT, (arr,))
    return np.asarray(out)


def _nmt_roots_np_batch(leaves: np.ndarray) -> np.ndarray:
    """Host reduction of a batch of NMTs: uint8[T, n, L] -> uint8[T, 90].

    Mirror of :func:`nmt_roots` in numpy — the no-native fallback of
    :func:`eds_nmt_roots_host`.  Hashing runs SERIALLY (nthreads=1):
    this executes inside a pool worker, and fanning out again onto the
    same executor would deadlock it (all workers blocked on futures only
    they could run)."""
    from celestia_tpu.ops.sha256 import sha256_batch_host

    T, n, L = leaves.shape
    ns = leaves[:, :, :NAMESPACE_SIZE]
    prefix = np.zeros((T, n, 1), dtype=np.uint8)
    h = sha256_batch_host(
        np.concatenate([prefix, leaves], axis=-1).reshape(T * n, L + 1),
        # celint: allow(hostpool-discipline) — deliberate serial: this
        # runs INSIDE a pool worker; fanning out onto the same executor
        # would deadlock it (all workers blocked on futures only they
        # could run)
        nthreads=1,
    ).reshape(T, n, 32)
    nodes = np.concatenate([ns, ns, h], axis=-1)
    while nodes.shape[1] > 1:
        left, right = nodes[:, 0::2], nodes[:, 1::2]
        l_max = left[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        r_min = right[..., :NAMESPACE_SIZE]
        r_max = right[..., NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        r_is_parity = np.all(r_min == _PARITY_NS, axis=-1, keepdims=True)
        max_ns = np.where(r_is_parity, l_max, r_max)
        one = np.ones(left.shape[:-1] + (1,), dtype=np.uint8)
        h = sha256_batch_host(
            np.concatenate([one, left, right], axis=-1).reshape(
                -1, 1 + 2 * NMT_DIGEST_SIZE
            ),
            # celint: allow(hostpool-discipline) — same nested-pool
            # deadlock avoidance as the leaf pass above
            nthreads=1,
        ).reshape(left.shape[:-1] + (32,))
        nodes = np.concatenate(
            [left[..., :NAMESPACE_SIZE], max_ns, h], axis=-1
        )
    return nodes[:, 0]


def eds_nmt_roots_host(eds: np.ndarray, nthreads=None) -> np.ndarray:
    """All 4k NMT axis roots on the HOST worker pool (no device, no XLA
    compile): uint8[2k, 2k, B] -> uint8[2, 2k, 90].

    The 4k trees are embarrassingly parallel; the native C++ entry
    shards them across the pool, and the numpy fallback shards
    tree-chunks across the same pool.  Byte-identical to
    :func:`eds_nmt_roots` (pinned by tests/test_sha_nmt.py and the
    thread-scaling tests in tests/test_leopard_codec.py)."""
    from celestia_tpu.utils import hostpool, native

    eds = np.ascontiguousarray(eds, dtype=np.uint8)
    n2 = eds.shape[0]
    if native.available():
        return native.eds_nmt_roots(eds, nthreads=nthreads).reshape(
            2, n2, NMT_DIGEST_SIZE
        )
    # numpy fallback: build the prefixed leaves, then reduce tree-chunks
    # on the shared pool
    k = n2 // 2
    own_ns = eds[..., :NAMESPACE_SIZE]
    parity = np.broadcast_to(_PARITY_NS, own_ns.shape)
    r = np.arange(n2)
    in_q0 = (r[:, None] < k) & (r[None, :] < k)
    prefix = np.where(in_q0[..., None], own_ns, parity)
    rows = np.concatenate([prefix, eds], axis=-1)
    trees = np.concatenate([rows, rows.transpose(1, 0, 2)], axis=0)
    workers = nthreads if nthreads is not None else hostpool.cpu_threads()
    workers = max(1, min(int(workers), trees.shape[0]))
    bounds = np.linspace(0, trees.shape[0], workers + 1).astype(int)
    chunks = hostpool.run_sharded(
        lambda t: _nmt_roots_np_batch(trees[bounds[t] : bounds[t + 1]]),
        range(workers),
    )
    return np.concatenate(chunks, axis=0).reshape(2, n2, NMT_DIGEST_SIZE)


def nmt_roots_host_batch(leaves: np.ndarray, nthreads=None) -> np.ndarray:
    """Roots of an ARBITRARY batch of NMTs on the host: uint8[T, n, L]
    namespace-prefixed leaves -> uint8[T, 90], threaded.

    The selective counterpart of :func:`eds_nmt_roots_host` — the row-memo
    path in da/dah.py only needs the trees the memo missed (changed rows,
    parity rows, columns), not all 4k.  Pool-sharded numpy: the memo's
    native leg deliberately prefers the full fused C++ root pass over a
    selective reduction (measured faster even with most rows memoized —
    da/dah.py), so this only ever runs in the no-native fallback."""
    from celestia_tpu.utils import hostpool

    leaves = np.ascontiguousarray(leaves, dtype=np.uint8)
    if leaves.ndim != 3:
        raise ValueError(f"leaves must be [T, n, L], got {leaves.shape}")
    T, n, _L = leaves.shape
    if T == 0:
        return np.zeros((0, NMT_DIGEST_SIZE), dtype=np.uint8)
    if n & (n - 1):
        raise ValueError(f"leaf count must be a power of two, got {n}")
    workers = nthreads if nthreads is not None else hostpool.cpu_threads()
    workers = max(1, min(int(workers), T))
    bounds = np.linspace(0, T, workers + 1).astype(int)
    chunks = hostpool.run_sharded(
        lambda t: _nmt_roots_np_batch(leaves[bounds[t] : bounds[t + 1]]),
        range(workers),
    )
    return np.concatenate(chunks, axis=0)


def empty_root_np() -> np.ndarray:
    """EmptyRoot: zeros ns range + sha256 of the empty string."""
    import hashlib

    return np.frombuffer(
        b"\x00" * (2 * NAMESPACE_SIZE) + hashlib.sha256(b"").digest(), dtype=np.uint8
    )


# ---------------------------------------------------------------------------
# RFC-6962-style binary Merkle tree (tendermint/go-square merkle parity)
# used for the data root over the 4k NMT axis roots
# (pkg/da/data_availability_header.go:92-108) and share commitments.
# ---------------------------------------------------------------------------


def rfc6962_leaf_hashes(leaves: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n, L] -> uint8[..., n, 32]: sha256(0x00 || leaf)."""
    prefix = jnp.zeros(leaves.shape[:-1] + (1,), dtype=jnp.uint8)
    return sha256(jnp.concatenate([prefix, leaves], axis=-1))


def rfc6962_inner(left: jnp.ndarray, right: jnp.ndarray) -> jnp.ndarray:
    prefix = jnp.ones(left.shape[:-1] + (1,), dtype=jnp.uint8)
    return sha256(jnp.concatenate([prefix, left, right], axis=-1))


def rfc6962_root_pow2(leaves: jnp.ndarray) -> jnp.ndarray:
    """Merkle root of a power-of-two number of equal-length leaves.

    uint8[..., n, L] -> uint8[..., 32].  Matches tendermint's simple merkle
    for power-of-two counts (split point = n/2 at every level).
    """
    n = leaves.shape[-2]
    if n & (n - 1):
        raise ValueError(f"leaf count must be a power of two, got {n}")
    nodes = rfc6962_leaf_hashes(leaves)
    while nodes.shape[-2] > 1:
        nodes = rfc6962_inner(nodes[..., 0::2, :], nodes[..., 1::2, :])
    return nodes[..., 0, :]


def rfc6962_level_stack(leaves: jnp.ndarray) -> list:
    """All levels of the RFC-6962 tree over a power-of-two leaf count:
    ``[leaf hashes (..., n, 32), (..., n/2, 32), ..., root (..., 1, 32)]``.

    Traceable twin of da/proof.py's host ``merkle_level_tree`` (pinned
    byte-identical by tests/test_device_plane.py) — the device-resident
    plane keeps this stack on-chip so a data-root membership proof is a
    gather of ``levels[j][(index >> j) ^ 1]``, never a re-hash.
    """
    n = leaves.shape[-2]
    if n & (n - 1):
        raise ValueError(f"leaf count must be a power of two, got {n}")
    levels = [rfc6962_leaf_hashes(leaves)]
    while levels[-1].shape[-2] > 1:
        nodes = levels[-1]
        levels.append(rfc6962_inner(nodes[..., 0::2, :], nodes[..., 1::2, :]))
    return levels


def rfc6962_root_np(leaves: list) -> np.ndarray:
    """Host reference for arbitrary leaf counts (tendermint split rule:
    largest power of two strictly less than n)."""
    import hashlib

    def rec(items):
        if len(items) == 0:
            return hashlib.sha256(b"").digest()
        if len(items) == 1:
            return hashlib.sha256(b"\x00" + items[0]).digest()
        split = 1
        while split * 2 < len(items):
            split *= 2
        left = rec(items[:split])
        right = rec(items[split:])
        return hashlib.sha256(b"\x01" + left + right).digest()

    return np.frombuffer(rec([bytes(x) for x in leaves]), dtype=np.uint8)


def nmt_level_stack(leaves: jnp.ndarray) -> list:
    """All levels of the NMT: [leaf digests (n), level1 (n/2), ..., root (1)].

    The level stack is what proof generation needs (inner nodes at every
    aligned span) — the reference gets these via the NodeVisitor cache
    (pkg/inclusion/nmt_caching.go:80-124); here they fall out of the
    level-synchronous reduction for free.
    """
    n = leaves.shape[-2]
    if n & (n - 1):
        raise ValueError(f"leaf count must be a power of two, got {n}")
    levels = [leaf_digests(leaves)]
    while levels[-1].shape[-2] > 1:
        levels.append(combine_level(levels[-1]))
    return levels


def combine_digests_np(left: bytes, right: bytes) -> bytes:
    """Host-side NMT node combine (for proof verification)."""
    import hashlib

    l_min, l_max = left[:NAMESPACE_SIZE], left[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
    r_min, r_max = right[:NAMESPACE_SIZE], right[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
    max_ns = l_max if r_min == bytes(_PARITY_NS) else r_max
    h = hashlib.sha256(b"\x01" + left + right).digest()
    return l_min + max_ns + h


def leaf_digest_np(ns_prefixed_leaf: bytes) -> bytes:
    """Host-side NMT leaf digest (for proof verification)."""
    import hashlib

    ns = ns_prefixed_leaf[:NAMESPACE_SIZE]
    return ns + ns + hashlib.sha256(b"\x00" + ns_prefixed_leaf).digest()
