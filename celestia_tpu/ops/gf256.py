"""GF(2^8) arithmetic and Reed-Solomon matrices (host side, numpy).

This is the TPU build's equivalent of the reference's erasure codec
(rsmt2d.Codec backed by klauspost/reedsolomon "Leopard", selected at
/root/reference/pkg/appconsts/global_consts.go:91-92).  Instead of an
O(n log n) FFT codec with SIMD assembly, we use a systematic
Lagrange-evaluation RS code whose encode/decode are *matrices* over GF(256),
lowered to GF(2) bit-matrices so the device can run them as plain integer
matmuls on the MXU (see ops/rs.py).  For the protocol's k <= 128 this is
exact, deterministic, and maps perfectly onto the 128x128 systolic array.

Code definition: a row of k data shares is a polynomial sampled at field
points 0..k-1; parity shares are its evaluations at points k..2k-1.  Any k
of the 2k points reconstruct the rest (Lagrange interpolation) — the same
25%-withholding recovery property rsmt2d relies on for DAS.

Field: GF(2^8) with primitive polynomial 0x11D (x^8+x^4+x^3+x^2+1).
All matrices here are cached per square size; everything downstream is
bit-exact across backends because the device path is integer-only.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

_PRIM_POLY = 0x11D
_ORDER = 255

# --- log/antilog tables -----------------------------------------------------


def _build_tables():
    exp = np.zeros(512, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(_ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _PRIM_POLY
    for i in range(_ORDER, 512):
        exp[i] = exp[i - _ORDER]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a, b):
    """Element-wise GF(256) multiply over numpy uint8 arrays (or scalars)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = GF_EXP[(GF_LOG[a.astype(np.int32)] + GF_LOG[b.astype(np.int32)]) % _ORDER]
    out = np.where((a == 0) | (b == 0), 0, out)
    return out.astype(np.uint8)


def gf_inv(a):
    a = np.asarray(a, dtype=np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("GF(256) inverse of zero")
    return GF_EXP[(_ORDER - GF_LOG[a.astype(np.int32)]) % _ORDER].astype(np.uint8)


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(256) matrix product (host reference; small matrices only)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for j in range(a.shape[1]):
        prod = gf_mul(a[:, j : j + 1], b[j : j + 1, :])
        out ^= prod
    return out


# --- Lagrange evaluation matrices -------------------------------------------


def lagrange_matrix(src_points: np.ndarray, dst_points: np.ndarray) -> np.ndarray:
    """M[i, j] such that f(dst_i) = sum_j M[i,j] * f(src_j) in GF(256).

    src_points must be distinct; dst may overlap src (rows become unit rows).
    Vectorized via log-domain products.
    """
    src = np.asarray(src_points, dtype=np.uint8)
    dst = np.asarray(dst_points, dtype=np.uint8)
    k = len(src)
    if len(np.unique(src)) != k:
        raise ValueError("source points must be distinct")
    # denom_j = prod_{m != j} (src_j ^ src_m)
    diff_ss = src[None, :] ^ src[:, None]  # [j, m]
    np.fill_diagonal(diff_ss, 1)  # neutral in the product
    denom_log = GF_LOG[diff_ss.astype(np.int32)].sum(axis=1) % _ORDER  # [j]
    # num_{i,j} = prod_{m != j} (dst_i ^ src_m)
    diff_ds = dst[:, None] ^ src[None, :]  # [i, m]
    zero_mask = diff_ds == 0  # dst_i == src_m
    safe = np.where(zero_mask, 1, diff_ds)
    log_all = GF_LOG[safe.astype(np.int32)]
    total_log = log_all.sum(axis=1)  # [i] — includes m == j term
    n_zeros = zero_mask.sum(axis=1)  # [i]
    M = np.zeros((len(dst), k), dtype=np.uint8)
    for i in range(len(dst)):
        if n_zeros[i] > 0:
            # dst_i coincides with some src point: unit row.
            j = int(np.nonzero(zero_mask[i])[0][0])
            M[i, j] = 1
            continue
        num_log = (total_log[i] - log_all[i]) % _ORDER  # [j]
        M[i] = GF_EXP[(num_log - denom_log) % _ORDER]
    return M


@lru_cache(maxsize=None)
def encode_matrix(k: int) -> np.ndarray:
    """E (k x k): parity shares k..2k-1 from data shares 0..k-1."""
    if not 1 <= k <= 128:
        raise ValueError(f"square size k must be in [1, 128], got {k}")
    pts = np.arange(2 * k, dtype=np.uint8)
    return lagrange_matrix(pts[:k], pts[k:])


def decode_matrix(known_points: np.ndarray, k: int) -> np.ndarray:
    """D (2k x k): all 2k shares from the k known-point shares."""
    known = np.asarray(known_points, dtype=np.uint8)
    if len(known) != k:
        raise ValueError(f"need exactly {k} known points, got {len(known)}")
    return lagrange_matrix(known, np.arange(2 * k, dtype=np.uint8))


def decode_matrices_batch(known_batch: np.ndarray, k: int) -> np.ndarray:
    """Per-axis decode matrices, vectorized: known_batch uint8[n, k] (each
    row k distinct points) -> D uint8[n, 2k, k].

    The fully-vectorized form of :func:`decode_matrix` over a batch of
    axes — repair of a DAS-withheld square needs one matrix per axis (every
    axis can have a different availability mask), and building them one
    Python call at a time dominates repair time at k=128.
    """
    src = np.asarray(known_batch, dtype=np.uint8)
    n = src.shape[0]
    if src.shape != (n, k):
        raise ValueError(f"known_batch must be (n, {k}), got {src.shape}")
    # consensus-critical math must fail loud: a repeated point would turn
    # the log-domain denominators into silent garbage
    sorted_src = np.sort(src, axis=1)
    if k > 1 and (sorted_src[:, 1:] == sorted_src[:, :-1]).any():
        raise ValueError("source points must be distinct within each axis")
    dst = np.arange(2 * k, dtype=np.uint8)
    # denominators: denom_log[b, j] = sum_{m != j} log(src_j ^ src_m)
    diff_ss = src[:, None, :] ^ src[:, :, None]  # [b, j, m]
    diag = np.arange(k)
    diff_ss[:, diag, diag] = 1  # neutral in the log-sum
    denom_log = GF_LOG[diff_ss.astype(np.int32)].sum(axis=2) % _ORDER  # [b, j]
    # numerators: for every dst_i, prod_{m != j} (dst_i ^ src_m)
    diff_ds = dst[None, :, None] ^ src[:, None, :]  # [b, i, m]
    zero_mask = diff_ds == 0  # dst_i == src_m (at most one m per (b, i))
    safe = np.where(zero_mask, 1, diff_ds)
    log_all = GF_LOG[safe.astype(np.int32)]  # [b, i, m]
    total_log = log_all.sum(axis=2)  # [b, i]
    has_zero = zero_mask.any(axis=2)  # [b, i]
    num_log = (total_log[:, :, None] - log_all) % _ORDER  # [b, i, j]
    lagrange = GF_EXP[(num_log - denom_log[:, None, :]) % _ORDER]
    # rows where dst coincides with a src point are unit rows — zero_mask
    # is exactly that one-hot (src points are distinct per axis)
    return np.where(
        has_zero[:, :, None], zero_mask.astype(np.uint8), lagrange
    ).astype(np.uint8)


# --- GF(2) bit-expansion ----------------------------------------------------
#
# Multiplication by a constant c in GF(2^8) is GF(2)-linear on the bits of the
# operand: bit s of (c*b) = XOR_t M_c[s,t]*b_t with M_c[s,t] = bit s of
# (c * 2^t).  A GF(256) matrix A (m x n) therefore lifts to a binary matrix
# A_bits (8m x 8n) and "y = A x over GF(256)" becomes
# "y_bits = A_bits @ x_bits mod 2" — an integer matmul the MXU executes
# natively (int8 inputs, int32 accumulation), with the mod-2 as a cheap
# elementwise mask.


def bit_expand_matrix(A: np.ndarray) -> np.ndarray:
    """Lift a GF(256) matrix (m x n) to its GF(2) form (8m x 8n), int8 0/1.

    Row index i*8+s = output bit s of GF-row i; column index j*8+t = input
    bit t of GF-column j.
    """
    A = np.asarray(A, dtype=np.uint8)
    m, n = A.shape
    powers = (np.uint8(1) << np.arange(8, dtype=np.uint8))  # 2^t
    # prod[m_i, n_j, t] = A[i,j] * 2^t in GF(256)
    prod = gf_mul(A[:, :, None], powers[None, None, :])  # (m, n, 8) uint8
    # bits[s] of prod -> out[(i,s),(j,t)]
    s_idx = np.arange(8, dtype=np.uint8)
    bits = (prod[:, :, None, :] >> s_idx[None, None, :, None]) & 1  # (m, n, s, t)
    out = bits.transpose(0, 2, 1, 3).reshape(8 * m, 8 * n)
    return out.astype(np.int8)


@lru_cache(maxsize=None)
def encode_matrix_bits(k: int) -> np.ndarray:
    """Bit-expanded encode matrix (8k x 8k), int8 0/1 — the MXU operand."""
    return bit_expand_matrix(encode_matrix(k))


# --- Host reference encode (for bit-exactness tests) ------------------------


def encode_shares_ref(data: np.ndarray) -> np.ndarray:
    """Reference row-encode: data (k, B) uint8 -> parity (k, B) uint8.

    Direct table-lookup GF matmul; the device path in ops/rs.py must match
    this bit-for-bit.
    """
    data = np.asarray(data, dtype=np.uint8)
    k = data.shape[0]
    E = encode_matrix(k)
    out = np.zeros_like(data)
    for j in range(k):
        out ^= gf_mul(E[:, j : j + 1], data[j : j + 1, :])
    return out
