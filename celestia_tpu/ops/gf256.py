"""GF(2^8) arithmetic and Reed-Solomon matrices (host side, numpy).

This is the TPU build's equivalent of the reference's erasure codec
(rsmt2d.Codec backed by klauspost/reedsolomon "Leopard", selected at
/root/reference/pkg/appconsts/global_consts.go:91-92).  Instead of an
O(n log n) FFT codec with SIMD assembly, we use a systematic
Lagrange-evaluation RS code whose encode/decode are *matrices* over GF(256),
lowered to GF(2) bit-matrices so the device can run them as plain integer
matmuls on the MXU (see ops/rs.py).  For the protocol's k <= 128 this is
exact, deterministic, and maps perfectly onto the 128x128 systolic array.

Code definition: a row of k data shares is a polynomial sampled at k field
points; parity shares are its evaluations at k more points.  Any k of the
2k points reconstruct the rest (Lagrange interpolation) — the same
25%-withholding recovery property rsmt2d relies on for DAS.  Two codecs
share this machinery (see "codec selection" below): "leopard-ff8"
reproduces the reference chain's Leopard parity bytes exactly, and
"lagrange-gf256" is the original standard-basis code.

Field: GF(2^8) with primitive polynomial 0x11D (x^8+x^4+x^3+x^2+1).
All matrices here are cached per square size; everything downstream is
bit-exact across backends because the device path is integer-only.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

_PRIM_POLY = 0x11D
_ORDER = 255

# --- log/antilog tables -----------------------------------------------------


def _build_tables():
    exp = np.zeros(512, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(_ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _PRIM_POLY
    for i in range(_ORDER, 512):
        exp[i] = exp[i - _ORDER]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


# --- codec selection ---------------------------------------------------------
#
# Two share codecs, selectable per chain (pinned in genesis, ADR-012):
#
# - "leopard-ff8" (DEFAULT): byte-compatible with the reference chain's
#   Leopard codec (rsmt2d.NewLeoRSCodec at
#   /root/reference/pkg/appconsts/global_consts.go:91-92, backed by
#   klauspost/reedsolomon's port of catid/leopard FF8).  Leopard's tables
#   represent field elements in the CANTOR-INDEX domain: byte value v
#   stands for the field element C(v) = XOR of Cantor basis vectors
#   selected by v's bits, and multiplication is conjugated through that
#   bijection.  A systematic MDS RS code's parity is uniquely determined
#   by the field, the evaluation points, and the data/parity position
#   layout — independent of the encode algorithm — so the MXU matmul
#   pipeline reproduces Leopard's exact parity bytes by simply using the
#   conjugated field tables and Leopard's high-rate layout (parity at
#   positions [0, k), data at [k, 2k); position -> point is XOR with k).
#   Multiplication by a constant is still GF(2)-linear in the operand's
#   bits (C is GF(2)-linear), so the bit-matrix lift below is unchanged.
# - "lagrange-gf256": this repo's original codec (points 0..2k-1 in the
#   standard polynomial basis, data first).  Kept for chains that pinned
#   it at genesis before ADR-012.

CODEC_LEOPARD = "leopard-ff8"
CODEC_LAGRANGE = "lagrange-gf256"
CODECS = (CODEC_LEOPARD, CODEC_LAGRANGE)

# catid/leopard FF8 Cantor basis: beta_0 = 1 and each beta_i is the
# lexicographically smaller root of x^2 + x = beta_{i-1} in
# GF(2^8)/0x11D (derivation pinned by tests/test_leopard_codec.py).
CANTOR_BASIS = (1, 214, 152, 146, 86, 200, 88, 230)


def _build_leopard_tables():
    """Field tables for the Cantor-index representation: byte v stands
    for field element C(v); mul'(a, b) = C^-1(C(a) * C(b))."""
    C = np.zeros(256, dtype=np.uint8)
    for j, beta in enumerate(CANTOR_BASIS):
        w = 1 << j
        C[w : 2 * w] = C[:w] ^ beta
    Cinv = np.zeros(256, dtype=np.uint8)
    Cinv[C] = np.arange(256, dtype=np.uint8)
    assert C[1] == 1, "C(1) must be the multiplicative identity"
    log = GF_LOG[C.astype(np.int32)].copy()  # log'[v] = log2(C(v))
    exp = np.zeros(512, dtype=np.int32)
    exp[:_ORDER] = Cinv[GF_EXP[:_ORDER]]
    exp[_ORDER : 2 * _ORDER] = exp[:_ORDER]
    return exp, log


LEO_EXP, LEO_LOG = _build_leopard_tables()

_FIELD_TABLES = {
    CODEC_LAGRANGE: (GF_EXP, GF_LOG),
    CODEC_LEOPARD: (LEO_EXP, LEO_LOG),
}

_ACTIVE_CODEC = CODEC_LEOPARD

# Pin-once-at-genesis enforcement (ROADMAP r5 follow-up): once the native
# library has loaded the active codec's MUL table (utils/native.py
# _ensure_field — the first "native use"), a codec SWITCH outside tests
# hard-fails instead of silently re-keying every downstream artifact.
# The utils/native.py field lock already closes the data race; this guard
# documents and enforces the INVARIANT: the codec is a consensus constant
# pinned at genesis (ADR-012), one chain per process, and everything keyed
# by it after first use — native field tables, jit caches, the EDS cache
# and row memo in da/ — assumes it never changes underneath them.
_codec_used = False


def active_codec() -> str:
    return _ACTIVE_CODEC


def mark_codec_used() -> None:
    """Called by utils/native.py when the process-global field tables are
    first loaded; from then on the active codec is frozen (see below)."""
    global _codec_used
    _codec_used = True


def codec_used() -> bool:
    return _codec_used


def _in_tests() -> bool:
    import os

    return "PYTEST_CURRENT_TEST" in os.environ


def set_active_codec(codec: str, force: bool = False) -> None:
    """Select the share codec process-wide (one chain per process; the
    app pins this from genesis at init — ADR-012).

    Re-pinning the SAME codec is always a no-op.  Switching codecs after
    the first native use refuses outside tests (``force=True`` or a
    running pytest session overrides — tests exercise both codecs in one
    process and re-derive every cached artifact per codec key)."""
    global _ACTIVE_CODEC
    if codec not in CODECS:
        raise ValueError(f"unknown codec {codec!r}; expected one of {CODECS}")
    if (
        codec != _ACTIVE_CODEC
        and _codec_used
        and not force
        and not _in_tests()
    ):
        raise RuntimeError(
            f"cannot switch the share codec from {_ACTIVE_CODEC!r} to "
            f"{codec!r}: the codec is a consensus constant pinned at genesis "
            "(ADR-012) and this process already computed with the active "
            "codec's field tables.  Start a fresh process for a chain with "
            "a different codec."
        )
    _ACTIVE_CODEC = codec


def _resolve(codec):
    return _ACTIVE_CODEC if codec is None else codec


def field_tables(codec: str = None):
    """(exp, log) int32 tables for the codec's field representation."""
    return _FIELD_TABLES[_resolve(codec)]


def position_points(positions, k: int, codec: str = None):
    """Map EDS axis positions (0..2k-1; data then parity) to field points.

    Leopard's high-rate layout puts parity at points [0, k) and data at
    [k, 2k); with k a power of two that is XOR with k.  The Lagrange
    codec evaluates data at 0..k-1 and parity at k..2k-1 directly."""
    pos = np.asarray(positions)
    if _resolve(codec) == CODEC_LEOPARD:
        return pos ^ k
    return pos


def gf_mul(a, b, codec: str = None):
    """Element-wise GF(256) multiply over numpy uint8 arrays (or scalars),
    in the active codec's field representation."""
    exp, log = field_tables(codec)
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = exp[(log[a.astype(np.int32)] + log[b.astype(np.int32)]) % _ORDER]
    out = np.where((a == 0) | (b == 0), 0, out)
    return out.astype(np.uint8)


def gf_inv(a, codec: str = None):
    exp, log = field_tables(codec)
    a = np.asarray(a, dtype=np.uint8)
    if np.any(a == 0):
        raise ZeroDivisionError("GF(256) inverse of zero")
    return exp[(_ORDER - log[a.astype(np.int32)]) % _ORDER].astype(np.uint8)


def gf_div(a, b, codec: str = None):
    return gf_mul(a, gf_inv(b, codec), codec)


def gf_matmul(a: np.ndarray, b: np.ndarray, codec: str = None) -> np.ndarray:
    """GF(256) matrix product (host reference; small matrices only)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for j in range(a.shape[1]):
        prod = gf_mul(a[:, j : j + 1], b[j : j + 1, :], codec)
        out ^= prod
    return out


def mul_table(codec: str = None) -> np.ndarray:
    """Full 256x256 multiplication table for the codec's field — loaded
    into the native C++ library so its table-method legs compute in the
    same representation as the device path."""
    v = np.arange(256, dtype=np.uint8)
    return gf_mul(v[:, None], v[None, :], codec)


# --- Lagrange evaluation matrices -------------------------------------------


def lagrange_matrix(
    src_points: np.ndarray, dst_points: np.ndarray, codec: str = None
) -> np.ndarray:
    """M[i, j] such that f(dst_i) = sum_j M[i,j] * f(src_j) in GF(256)
    (the codec's field representation).

    src_points must be distinct; dst may overlap src (rows become unit rows).
    Vectorized via log-domain products.
    """
    exp, log = field_tables(codec)
    src = np.asarray(src_points, dtype=np.uint8)
    dst = np.asarray(dst_points, dtype=np.uint8)
    k = len(src)
    if len(np.unique(src)) != k:
        raise ValueError("source points must be distinct")
    # denom_j = prod_{m != j} (src_j ^ src_m)
    diff_ss = src[None, :] ^ src[:, None]  # [j, m]
    np.fill_diagonal(diff_ss, 1)  # neutral in the product
    denom_log = log[diff_ss.astype(np.int32)].sum(axis=1) % _ORDER  # [j]
    # num_{i,j} = prod_{m != j} (dst_i ^ src_m)
    diff_ds = dst[:, None] ^ src[None, :]  # [i, m]
    zero_mask = diff_ds == 0  # dst_i == src_m
    safe = np.where(zero_mask, 1, diff_ds)
    log_all = log[safe.astype(np.int32)]
    total_log = log_all.sum(axis=1)  # [i] — includes m == j term
    n_zeros = zero_mask.sum(axis=1)  # [i]
    M = np.zeros((len(dst), k), dtype=np.uint8)
    for i in range(len(dst)):
        if n_zeros[i] > 0:
            # dst_i coincides with some src point: unit row.
            j = int(np.nonzero(zero_mask[i])[0][0])
            M[i, j] = 1
            continue
        num_log = (total_log[i] - log_all[i]) % _ORDER  # [j]
        M[i] = exp[(num_log - denom_log) % _ORDER]
    return M


@lru_cache(maxsize=None)
def _encode_matrix_cached(k: int, codec: str) -> np.ndarray:
    pos = np.arange(2 * k)
    pts = position_points(pos, k, codec).astype(np.uint8)
    return lagrange_matrix(pts[:k], pts[k:], codec)


def encode_matrix(k: int, codec: str = None) -> np.ndarray:
    """E (k x k): parity at positions k..2k-1 from data at 0..k-1."""
    if not 1 <= k <= 128:
        raise ValueError(f"square size k must be in [1, 128], got {k}")
    return _encode_matrix_cached(k, _resolve(codec))


def decode_matrix(
    known_positions: np.ndarray, k: int, codec: str = None
) -> np.ndarray:
    """D (2k x k): all 2k positions from the k known-position shares."""
    known = np.asarray(known_positions)
    if len(known) != k:
        raise ValueError(f"need exactly {k} known positions, got {len(known)}")
    codec = _resolve(codec)
    src = position_points(known, k, codec).astype(np.uint8)
    dst = position_points(np.arange(2 * k), k, codec).astype(np.uint8)
    return lagrange_matrix(src, dst, codec)


def decode_matrices_batch(
    known_batch: np.ndarray, k: int, codec: str = None
) -> np.ndarray:
    """Per-axis decode matrices, vectorized: known_batch uint8[n, k] (each
    row k distinct POSITIONS in 0..2k-1) -> D uint8[n, 2k, k].

    The fully-vectorized form of :func:`decode_matrix` over a batch of
    axes — repair of a DAS-withheld square needs one matrix per axis (every
    axis can have a different availability mask), and building them one
    Python call at a time dominates repair time at k=128.
    """
    codec = _resolve(codec)
    exp, log = field_tables(codec)
    positions = np.asarray(known_batch, dtype=np.uint8)
    n = positions.shape[0]
    if positions.shape != (n, k):
        raise ValueError(f"known_batch must be (n, {k}), got {positions.shape}")
    # consensus-critical math must fail loud: a repeated point would turn
    # the log-domain denominators into silent garbage
    sorted_src = np.sort(positions, axis=1)
    if k > 1 and (sorted_src[:, 1:] == sorted_src[:, :-1]).any():
        raise ValueError("source points must be distinct within each axis")
    src = position_points(positions, k, codec).astype(np.uint8)
    dst = position_points(np.arange(2 * k), k, codec).astype(np.uint8)
    # denominators: denom_log[b, j] = sum_{m != j} log(src_j ^ src_m)
    diff_ss = src[:, None, :] ^ src[:, :, None]  # [b, j, m]
    diag = np.arange(k)
    diff_ss[:, diag, diag] = 1  # neutral in the log-sum
    denom_log = log[diff_ss.astype(np.int32)].sum(axis=2) % _ORDER  # [b, j]
    # numerators: for every dst_i, prod_{m != j} (dst_i ^ src_m)
    diff_ds = dst[None, :, None] ^ src[:, None, :]  # [b, i, m]
    zero_mask = diff_ds == 0  # dst_i == src_m (at most one m per (b, i))
    safe = np.where(zero_mask, 1, diff_ds)
    log_all = log[safe.astype(np.int32)]  # [b, i, m]
    total_log = log_all.sum(axis=2)  # [b, i]
    has_zero = zero_mask.any(axis=2)  # [b, i]
    num_log = (total_log[:, :, None] - log_all) % _ORDER  # [b, i, j]
    lagrange = exp[(num_log - denom_log[:, None, :]) % _ORDER]
    # rows where dst coincides with a src point are unit rows — zero_mask
    # is exactly that one-hot (src points are distinct per axis)
    return np.where(
        has_zero[:, :, None], zero_mask.astype(np.uint8), lagrange
    ).astype(np.uint8)


# --- GF(2) bit-expansion ----------------------------------------------------
#
# Multiplication by a constant c in GF(2^8) is GF(2)-linear on the bits of the
# operand: bit s of (c*b) = XOR_t M_c[s,t]*b_t with M_c[s,t] = bit s of
# (c * 2^t).  A GF(256) matrix A (m x n) therefore lifts to a binary matrix
# A_bits (8m x 8n) and "y = A x over GF(256)" becomes
# "y_bits = A_bits @ x_bits mod 2" — an integer matmul the MXU executes
# natively (int8 inputs, int32 accumulation), with the mod-2 as a cheap
# elementwise mask.


def bit_expand_matrix(A: np.ndarray, codec: str = None) -> np.ndarray:
    """Lift a GF(256) matrix (m x n) to its GF(2) form (8m x 8n), int8 0/1.

    Row index i*8+s = output bit s of GF-row i; column index j*8+t = input
    bit t of GF-column j.  Valid for BOTH codec representations:
    multiplication by a constant stays GF(2)-linear in the operand's bits
    under the Cantor-index conjugation (C is GF(2)-linear).
    """
    A = np.asarray(A, dtype=np.uint8)
    m, n = A.shape
    powers = (np.uint8(1) << np.arange(8, dtype=np.uint8))  # 2^t
    # prod[m_i, n_j, t] = A[i,j] * 2^t in the codec's field
    prod = gf_mul(A[:, :, None], powers[None, None, :], codec)  # (m, n, 8)
    # bits[s] of prod -> out[(i,s),(j,t)]
    s_idx = np.arange(8, dtype=np.uint8)
    bits = (prod[:, :, None, :] >> s_idx[None, None, :, None]) & 1  # (m, n, s, t)
    out = bits.transpose(0, 2, 1, 3).reshape(8 * m, 8 * n)
    return out.astype(np.int8)


@lru_cache(maxsize=None)
def _encode_matrix_bits_cached(k: int, codec: str) -> np.ndarray:
    return bit_expand_matrix(encode_matrix(k, codec), codec)


def encode_matrix_bits(k: int, codec: str = None) -> np.ndarray:
    """Bit-expanded encode matrix (8k x 8k), int8 0/1 — the MXU operand."""
    return _encode_matrix_bits_cached(k, _resolve(codec))


# --- Host reference encode (for bit-exactness tests) ------------------------


def encode_shares_ref(data: np.ndarray, codec: str = None) -> np.ndarray:
    """Reference row-encode: data (k, B) uint8 -> parity (k, B) uint8.

    Direct table-lookup GF matmul; the device path in ops/rs.py must match
    this bit-for-bit.
    """
    data = np.asarray(data, dtype=np.uint8)
    k = data.shape[0]
    E = encode_matrix(k, codec)
    out = np.zeros_like(data)
    for j in range(k):
        out ^= gf_mul(E[:, j : j + 1], data[j : j + 1, :], codec)
    return out
