"""Batched SHA-256 on device (pure JAX, uint32 vector ops).

The reference's Merkle workload is thousands of independent SHA-256 calls per
block (NMT leaves/nodes via crypto/sha256, SURVEY.md §2.2 "NMT").  TPUs have
no crypto ISA, but the workload is embarrassingly parallel: we evaluate the
compression function as vectorized uint32 arithmetic over a large batch of
equal-length messages — message schedule and 64 rounds fully unrolled so XLA
fuses everything into a handful of elementwise kernels on the VPU.

Only fixed-length messages are needed (542-byte NMT leaves, 181-byte NMT
inner nodes, 91/65-byte RFC-6962 nodes), so padding is a compile-time
constant.  Bit-exact vs hashlib by construction (integer ops only); tested.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x: jnp.ndarray, r: int) -> jnp.ndarray:
    return (x >> np.uint32(r)) | (x << np.uint32(32 - r))


# The message schedule and the 64 rounds run as lax.scan loops (partially
# unrolled) rather than a fully unrolled graph: a fully unrolled 9-block
# message is ~5000 vector ops and takes minutes to compile; the scan version
# compiles in seconds and the body still fuses into a few VPU kernels over
# the whole hash batch.
_SCAN_UNROLL = 8


def _compress(state, block_words):
    """One SHA-256 compression: state tuple of 8 uint32[...], block [16][...]."""
    w16 = jnp.stack(block_words)  # [16, ...]

    def sched_step(window, _):
        s0 = _rotr(window[1], 7) ^ _rotr(window[1], 18) ^ (window[1] >> np.uint32(3))
        s1 = _rotr(window[14], 17) ^ _rotr(window[14], 19) ^ (window[14] >> np.uint32(10))
        new = window[0] + s0 + window[9] + s1
        return jnp.concatenate([window[1:], new[None]], axis=0), new

    _, w_rest = jax.lax.scan(sched_step, w16, None, length=48, unroll=_SCAN_UNROLL)
    w_all = jnp.concatenate([w16, w_rest], axis=0)  # [64, ...]

    def round_step(carry, xs):
        a, b, c, d, e, f, g, h = carry
        k_i, w_i = xs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_i + w_i
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    (a, b, c, d, e, f, g, h), _ = jax.lax.scan(
        round_step, state, (jnp.asarray(_K), w_all), unroll=_SCAN_UNROLL
    )
    s = state
    return (s[0] + a, s[1] + b, s[2] + c, s[3] + d,
            s[4] + e, s[5] + f, s[6] + g, s[7] + h)


@lru_cache(maxsize=None)
def _padding_bytes(msg_len: int) -> np.ndarray:
    """The constant SHA-256 padding for a message of ``msg_len`` bytes."""
    rem = (msg_len + 1 + 8) % 64
    zero_pad = (64 - rem) % 64
    pad = bytearray([0x80]) + bytes(zero_pad) + (msg_len * 8).to_bytes(8, "big")
    return np.frombuffer(bytes(pad), dtype=np.uint8)


def sha256(msgs: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 of a batch of equal-length messages.

    msgs: uint8[..., L] (L static) -> uint8[..., 32].  Jit-traceable.
    """
    L = msgs.shape[-1]
    lead = msgs.shape[:-1]
    pad = jnp.asarray(_padding_bytes(L))
    pad_full = jnp.broadcast_to(pad, lead + pad.shape)
    data = jnp.concatenate([msgs, pad_full], axis=-1)  # [..., n_blocks*64]
    n_blocks = data.shape[-1] // 64
    # big-endian uint32 words: [..., n_blocks, 16]
    b = data.reshape(lead + (n_blocks, 16, 4)).astype(jnp.uint32)
    words = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    state = tuple(jnp.broadcast_to(jnp.uint32(h), lead) for h in _H0)
    for blk in range(n_blocks):
        block_words = [words[..., blk, i] for i in range(16)]
        state = _compress(state, block_words)
    # serialize big-endian
    out = []
    for sw in state:
        out.append((sw >> np.uint32(24)).astype(jnp.uint8))
        out.append((sw >> np.uint32(16)).astype(jnp.uint8))
        out.append((sw >> np.uint32(8)).astype(jnp.uint8))
        out.append(sw.astype(jnp.uint8))
    return jnp.stack(out, axis=-1)


@lru_cache(maxsize=None)
def _sha256_jit(ndim: int):
    return jax.jit(sha256)


def sha256_np(msgs: np.ndarray) -> np.ndarray:
    """Convenience host entry: numpy in/out, jitted per input rank.

    As a standalone device dispatch it carries the devprof bracket
    (device-track timing + XLA cost accounting) — disabled, the bracket
    is one call returning a shared no-op."""
    from celestia_tpu.utils import devprof

    msgs = np.asarray(msgs, dtype=np.uint8)
    fn = _sha256_jit(msgs.ndim)
    arr = jnp.asarray(msgs)
    d = devprof.dispatch("sha256_batch", msg_len=int(msgs.shape[-1]))
    out = d.done(fn(arr))
    devprof.note_compile("sha256_batch", fn, (arr,))
    return np.asarray(out)


def sha256_batch_host(msgs: np.ndarray, nthreads=None) -> np.ndarray:
    """Batched SHA-256 on the HOST worker pool: uint8[n, L] -> uint8[n, 32].

    The host-regime counterpart of :func:`sha256` — native threaded
    SHA-NI when the C++ library is available, hashlib sharded across the
    process pool otherwise (hashlib releases the GIL, so the fallback
    scales too).  Bit-identical to the device path by construction."""
    from celestia_tpu.utils import hostpool, native

    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    if msgs.ndim != 2:
        raise ValueError(f"msgs must be [n, L], got {msgs.shape}")
    if native.available():
        return native.sha256_batch(msgs, nthreads=nthreads)
    import hashlib

    n = msgs.shape[0]
    workers = nthreads if nthreads is not None else hostpool.cpu_threads()
    workers = max(1, min(workers, n))
    out = np.zeros((n, 32), dtype=np.uint8)

    def shard(t: int) -> None:
        for i in range(t, n, workers):
            out[i] = np.frombuffer(
                hashlib.sha256(msgs[i].tobytes()).digest(), dtype=np.uint8
            )

    hostpool.run_sharded(shard, range(workers))
    return out
