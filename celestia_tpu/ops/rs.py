"""Device-side 2D Reed-Solomon extension and repair (JAX, MXU matmuls).

TPU-native equivalent of ``rsmt2d.ComputeExtendedDataSquare`` /
``rsmt2d.Repair`` as invoked by the reference at
/root/reference/pkg/da/data_availability_header.go:65-75 (encode) and its DAS
reconstruction surface (SURVEY.md §2.2).  Everything is integer arithmetic —
bit-exact across TPU/CPU backends and compiler versions, which is a consensus
-safety requirement (SURVEY.md §2.3 "determinism").

Representation: a square is ``uint8[k, k, 512]`` (row, column, byte).  GF(256)
linear maps are lifted to GF(2) bit-matrices (ops/gf256.py): shares are
unpacked to bit-planes, multiplied with an int8 0/1 matrix on the MXU with
int32 accumulation, reduced mod 2, and packed back to bytes.  The extension
is three batched matmuls (row parity, column parity, diagonal parity) fused
under one ``jit``.

Quadrant layout of the extended square (2k x 2k):

    Q0 | Q1        Q0 = original, Q1 = row parity,
    -------        Q2 = column parity, Q3 = parity of parity
    Q2 | Q3        (row- and column-extension commute; tested)
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from celestia_tpu.appconsts import SHARE_SIZE, is_power_of_two
from celestia_tpu.ops import gf256


def unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n, B] -> int8 bits[..., 8n, B]; bit row j*8+t = bit t of byte row j."""
    t = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> t[None, :, None]) & 1  # (..., n, 8, B)
    shape = x.shape[:-2] + (8 * x.shape[-2], x.shape[-1])
    return bits.reshape(shape).astype(jnp.int8)


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """int bits[..., 8n, B] -> uint8[..., n, B] (inverse of unpack_bits)."""
    shape = bits.shape[:-2] + (bits.shape[-2] // 8, 8, bits.shape[-1])
    b = bits.reshape(shape).astype(jnp.int32)
    t = jnp.arange(8, dtype=jnp.int32)
    return (b << t[None, :, None]).sum(axis=-2).astype(jnp.uint8)


def matmul_gf2(G: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    """(G @ bits) mod 2 with int32 MXU accumulation; operands int8 0/1."""
    acc = jnp.matmul(G, bits, preferred_element_type=jnp.int32)
    return (acc & 1).astype(jnp.int8)


def _row_parity(square: jnp.ndarray, G: jnp.ndarray) -> jnp.ndarray:
    """(r, k, B) uint8 -> (r, k, B) uint8 parity of each row."""
    bits = unpack_bits(square)  # (r, 8k, B)
    return pack_bits(matmul_gf2(G, bits))


def _extend(square: jnp.ndarray, G: jnp.ndarray) -> jnp.ndarray:
    """Core extension: uint8[k, k, B] -> uint8[2k, 2k, B]."""
    q0 = square
    q1 = _row_parity(q0, G)  # row parity
    q2 = _row_parity(q0.transpose(1, 0, 2), G).transpose(1, 0, 2)  # col parity
    q3 = _row_parity(q1.transpose(1, 0, 2), G).transpose(1, 0, 2)  # parity of parity
    top = jnp.concatenate([q0, q1], axis=1)
    bottom = jnp.concatenate([q2, q3], axis=1)
    return jnp.concatenate([top, bottom], axis=0)


@lru_cache(maxsize=None)
def _extend_fn(k: int, codec: str):
    # codec required — see _repair_verify_fn
    G = jnp.asarray(gf256.encode_matrix_bits(k, codec))
    return jax.jit(partial(_extend, G=G))


def extend_square(square) -> jnp.ndarray:
    """Extend an original square uint8[k, k, 512] to its EDS uint8[2k, 2k, 512].

    Device entry point: carries the devprof dispatch bracket (device
    track + cost accounting; a no-op when profiling is inactive — the
    result stays ASYNC then, exactly as before)."""
    from celestia_tpu.utils import devprof

    square = jnp.asarray(square, dtype=jnp.uint8)
    k = square.shape[0]
    if square.shape[1] != k or not is_power_of_two(k):
        raise ValueError(f"square must be (k, k, B) with k a power of two, got {square.shape}")
    fn = _extend_fn(k, gf256.active_codec())
    d = devprof.dispatch("rs_extend", k=k)
    out = d.done(fn(square))
    devprof.note_compile("rs_extend", fn, (square,))
    return out


@lru_cache(maxsize=None)
def _extend_batched_fn(k: int, codec: str):
    G = jnp.asarray(gf256.encode_matrix_bits(k, codec))
    return jax.jit(jax.vmap(partial(_extend, G=G)))


def extend_squares_batched(squares) -> jnp.ndarray:
    """Extend a batch uint8[n, k, k, 512] -> uint8[n, 2k, 2k, 512]."""
    from celestia_tpu.utils import devprof

    squares = jnp.asarray(squares, dtype=jnp.uint8)
    k = squares.shape[1]
    if squares.ndim != 4 or squares.shape[2] != k or not is_power_of_two(k):
        raise ValueError(
            f"batch must be (n, k, k, B) with k a power of two, got {squares.shape}"
        )
    fn = _extend_batched_fn(k, gf256.active_codec())
    d = devprof.dispatch("rs_extend_batched", k=k, n=int(squares.shape[0]))
    out = d.done(fn(squares))
    devprof.note_compile("rs_extend_batched", fn, (squares,))
    return out


# ---------------------------------------------------------------------------
# Device-side repair (rsmt2d.Repair on the MXU)
#
# Key observation: which axes become solvable in which order depends ONLY on
# the boolean availability mask, never on share values.  So the host
# simulates the peeling schedule on bools (microseconds), uploads the tiny
# per-phase index tensors (known positions + update masks, ~KB), and the
# device runs the entire data path: Lagrange decode-matrix construction in
# the log domain, the GF(2) bit-lift, and the batched decode as int8 MXU
# matmuls — the same arithmetic as the encode path, so it is bit-exact with
# the host reference.  No share byte crosses the PCIe/ICI link between
# phases.
# ---------------------------------------------------------------------------

def _gf_tables_dev(codec: str = None):
    # created per call, NOT cached: importing this module must not
    # initialize a jax backend, and a cached array captured inside a
    # traced scope would leak a tracer into later traces.  XLA folds
    # the repeated constants, so per-call creation costs nothing.
    exp, log = gf256.field_tables(codec)
    return (
        jnp.asarray(exp, dtype=jnp.int32),
        jnp.asarray(log, dtype=jnp.int32),
    )


def _decode_matrices_dev(
    known: jnp.ndarray, k: int, codec: str = None
) -> jnp.ndarray:
    """Device port of gf256.decode_matrices_batch: known uint8[n, k]
    (distinct POSITIONS per row — guaranteed by the host scheduler) ->
    D uint8[n, 2k, k].  Position -> field-point mapping is XOR with k
    under the leopard codec (gf256.position_points)."""
    codec = gf256._resolve(codec)
    exp, log = _gf_tables_dev(codec)
    xor_const = k if codec == gf256.CODEC_LEOPARD else 0
    src = known.astype(jnp.int32) ^ xor_const  # [n, k]
    dst = jnp.arange(2 * k, dtype=jnp.int32) ^ xor_const
    diff_ss = src[:, None, :] ^ src[:, :, None]  # [n, j, m]
    diff_ss = diff_ss.at[:, jnp.arange(k), jnp.arange(k)].set(1)
    denom_log = log[diff_ss].sum(axis=2) % 255  # [n, j]
    diff_ds = dst[None, :, None] ^ src[:, None, :]  # [n, i, m]
    zero_mask = diff_ds == 0
    safe = jnp.where(zero_mask, 1, diff_ds)
    log_all = log[safe]  # [n, i, m]
    total_log = log_all.sum(axis=2)  # [n, i]
    has_zero = zero_mask.any(axis=2)  # [n, i]
    num_log = (total_log[:, :, None] - log_all) % 255  # [n, i, j]
    lagrange = exp[(num_log - denom_log[:, None, :]) % 255]
    return jnp.where(
        has_zero[:, :, None], zero_mask.astype(jnp.uint8), lagrange
    ).astype(jnp.uint8)


@lru_cache(maxsize=None)
def _bit_basis(codec: str):
    """B[u, s, t] = bit s of gf_mul(2^u, 2^t) — the GF(2) lift is LINEAR
    in the operand's bits: M(a)[s,t] = XOR_u a_u * B[u,s,t].  Expanding a
    matrix therefore needs no table gathers (slow on TPU), just one tiny
    contraction over u against this 8x8x8 constant.  Holds in both codec
    representations (the Cantor-index map is GF(2)-linear)."""
    powers = np.uint8(1) << np.arange(8, dtype=np.uint8)
    prod = gf256.gf_mul(powers[:, None], powers[None, :], codec)  # [u, t]
    s = np.arange(8, dtype=np.uint8)
    return ((prod[:, None, :] >> s[None, :, None]) & 1).astype(np.int8)


def _bit_expand_dev(D: jnp.ndarray, codec: str = None) -> jnp.ndarray:
    """Device port of gf256.bit_expand_matrix, batched: uint8[n, m, c] ->
    int8 0/1 [n, 8m, 8c].  Gather-free: unpack D's bits, contract with
    the constant bit basis, mod 2."""
    n, m, c = D.shape
    u = jnp.arange(8, dtype=jnp.uint8)
    a_bits = ((D[:, :, :, None] >> u) & 1).astype(jnp.int8)  # [n, m, c, u]
    B = jnp.asarray(_bit_basis(gf256._resolve(codec)))  # [u, s, t]
    acc = jnp.einsum(
        "nmcu,ust->nmsct", a_bits, B, preferred_element_type=jnp.int32
    )
    out = (acc & 1).astype(jnp.int8)
    return out.reshape(n, 8 * m, 8 * c)


def _decode_axes_dev(
    data: jnp.ndarray, known: jnp.ndarray, k: int, chunk: int,
    codec: str = None,
) -> jnp.ndarray:
    """Decode ALL 2k axes of one orientation: data uint8[2k, 2k, B]
    (axis-major), known uint8[2k, k] -> decoded uint8[2k, 2k, B].
    Chunked over axes to bound the D_bits working set."""
    codec = gf256._resolve(codec)
    n2 = 2 * k
    B = data.shape[2]
    X = jnp.take_along_axis(data, known[:, :, None].astype(jnp.int32), axis=1)

    def one_chunk(args):
        Xc, knownc = args  # [chunk, k, B], [chunk, k]
        D = _decode_matrices_dev(knownc, k, codec)  # [chunk, 2k, k]
        D_bits = _bit_expand_dev(D, codec)  # [chunk, 16k, 8k]
        X_bits = unpack_bits(Xc)  # [chunk, 8k, B]
        out_bits = matmul_gf2(D_bits, X_bits)  # [chunk, 16k, B]
        return pack_bits(out_bits)  # [chunk, 2k, B]

    n_chunks = max(1, n2 // chunk)
    chunk = n2 // n_chunks
    Xr = X.reshape(n_chunks, chunk, k, B)
    Kr = known.reshape(n_chunks, chunk, k)
    decoded = jax.lax.map(one_chunk, (Xr, Kr))  # [n_chunks, chunk, 2k, B]
    return decoded.reshape(n2, n2, B)


def _repair_phases(
    eds: jnp.ndarray,
    row_known: jnp.ndarray,  # [P, 2k, k]
    row_mask: jnp.ndarray,  # [P, 2k] bool
    col_known: jnp.ndarray,
    col_mask: jnp.ndarray,
    k: int,
    chunk: int,
    codec: str = None,
) -> jnp.ndarray:
    """P peeling phases (rows then columns each), fully on device."""
    codec = gf256._resolve(codec)
    P = row_known.shape[0]
    for p in range(P):  # P is static: unrolled into one XLA program
        decoded = _decode_axes_dev(eds, row_known[p], k, chunk, codec)
        eds = jnp.where(row_mask[p][:, None, None], decoded, eds)
        edsT = eds.transpose(1, 0, 2)
        decodedT = _decode_axes_dev(edsT, col_known[p], k, chunk, codec)
        edsT = jnp.where(col_mask[p][:, None, None], decodedT, edsT)
        eds = edsT.transpose(1, 0, 2)
    return eds


def _repair_verify(
    eds, avail, row_known, row_mask, col_known, col_mask, *, k: int,
    chunk: int, with_roots: bool, codec: str = None,
):
    """Phases + BOTH byzantine checks (+ axis roots) fused into ONE
    device program — a repairing light/full node pays a single round trip
    for everything except the (optional) bulk fetch of the square.

    eds arrives with unavailable cells zeroed, so comparing the repaired
    square against it AT AVAILABLE CELLS is exactly the provided-share
    consistency check (rsmt2d ErrByzantine for shares the peeling
    schedule overwrote)."""
    codec = gf256._resolve(codec)
    repaired = _repair_phases(
        eds, row_known, row_mask, col_known, col_mask, k=k, chunk=chunk,
        codec=codec,
    )
    G = jnp.asarray(gf256.encode_matrix_bits(k, codec))
    recomputed = _extend(repaired[:k, :k], G)
    mismatch = jnp.any(repaired != recomputed, axis=2)  # [2k, 2k] bool
    provided_mismatch = avail & jnp.any(repaired != eds, axis=2)
    if with_roots:
        from celestia_tpu.ops import nmt as nmt_ops

        roots = nmt_ops.eds_nmt_roots(repaired)  # [2, 2k, 90]
    else:
        roots = jnp.zeros((2, 2 * k, 90), dtype=jnp.uint8)
    return repaired, mismatch, provided_mismatch, roots


# Honest DAS masks peel in 1-2 phases; each extra phase unrolls another
# full decode pipeline into the XLA program.  Bounding the device path (and
# the executable cache) stops an adversarial staircase mask from forcing
# unbounded multi-second recompiles — deeper peels take the host path.
_MAX_DEVICE_PHASES = 4


@lru_cache(maxsize=8)
def _repair_verify_fn(
    k: int, phases: int, chunk: int, with_roots: bool, codec: str
):
    # codec is REQUIRED (resolved by the caller): a None default resolved
    # in here would cache the first-build codec under key None and serve
    # a stale program after a codec switch
    return jax.jit(
        partial(
            _repair_verify, k=k, chunk=chunk, with_roots=with_roots,
            codec=codec,
        )
    )


def _simulate_schedule(avail: np.ndarray, k: int):
    """Peel the availability mask on the host (bools only): returns the
    per-phase (row_known, row_mask, col_known, col_mask) tensors the
    device program consumes.  Raises if the mask cannot reconstruct."""
    n2 = 2 * k
    avail = avail.copy()
    row_known, row_mask, col_known, col_mask = [], [], [], []

    def plan(mask2d):
        counts = mask2d.sum(axis=1)
        solvable = (counts >= k) & (counts < n2)
        # first k available positions per axis (arbitrary valid points for
        # unsolvable axes — their results are masked out)
        order = np.argsort(~mask2d, axis=1, kind="stable")
        known = np.sort(order[:, :k], axis=1).astype(np.uint8)
        known[~solvable] = np.arange(k, dtype=np.uint8)[None, :]
        return known, solvable

    while not avail.all():
        rk, rm = plan(avail)
        avail[rm] = True
        ck, cm = plan(avail.T)
        avail[:, cm] = True
        if not (rm.any() or cm.any()):
            raise ValueError(
                "repair stalled: insufficient available cells to reconstruct"
            )
        row_known.append(rk)
        row_mask.append(rm)
        col_known.append(ck)
        col_mask.append(cm)
    if not row_known:  # nothing missing: zero phases
        return None
    return (
        np.stack(row_known),
        np.stack(row_mask),
        np.stack(col_known),
        np.stack(col_mask),
    )


def repair_square_device(
    eds: np.ndarray,
    available: np.ndarray,
    row_roots: np.ndarray = None,
    col_roots: np.ndarray = None,
    breakdown: dict = None,
    return_device: bool = False,
) -> np.ndarray:
    """rsmt2d.Repair on the accelerator (VERDICT r2 #6 / BASELINE #4).

    Same contract as :func:`repair_square` — reconstruct, then prove the
    result is the unique codeword matching everything the caller provided
    (ByzantineError otherwise) and the committed DAH roots when given —
    but the decode matmuls, BOTH byzantine checks (codeword consistency
    AND provided-share agreement) and the NMT roots all run as ONE fused
    device program; the host only peels the boolean mask and ships index
    tensors, then fetches the small verdicts in one batched round trip.
    The bulk upload is kicked asynchronously before the host peel, so
    the transfer streams while the schedule is computed.

    ``return_device=True`` is the DOCUMENTED DEFAULT for DAS-serving
    callers: the repaired square stays in device memory (shares are
    re-served from there) with no loss of verification, skipping the
    bulk device->host fetch entirely.  Fetch only when the caller
    actually consumes the bytes host-side.

    breakdown (optional dict) receives schedule (overlapped with the
    upload) / upload_compute / verdict_fetch millisecond attributions,
    plus bulk_fetch_ms when the square is fetched."""
    import time as _t

    provided = np.asarray(eds, dtype=np.uint8)
    avail = np.asarray(available, dtype=bool)
    n2 = provided.shape[0]
    k = n2 // 2
    if provided.shape[:2] != (n2, n2) or avail.shape != (n2, n2):
        raise ValueError("eds must be (2k, 2k, B) with matching availability mask")
    masked = np.where(avail[:, :, None], provided, 0).astype(np.uint8)

    t0 = _t.time()
    schedule = _simulate_schedule(avail, k)  # bools only, ~1 ms at k=128
    if schedule is None:
        P = 0
        rk = np.zeros((0, n2, k), dtype=np.uint8)
        rm = np.zeros((0, n2), dtype=bool)
        ck, cm = rk.copy(), rm.copy()
    else:
        rk, rm, ck, cm = schedule
        P = rk.shape[0]
    if P > _MAX_DEVICE_PHASES:
        # degenerate (adversarial) masks: don't let each one compile its
        # own P-phase device program — the host path handles any depth.
        # (The bulk upload is dispatched AFTER this check so the
        # fallback never pays a wasted 8 MiB transfer.)
        out = repair_square(eds, available, row_roots, col_roots)
        return jnp.asarray(out) if return_device else out
    chunk = min(n2, max(1, 8192 // k))  # ~bounded D_bits working set
    with_roots = row_roots is not None or col_roots is not None
    # dispatch the bulk upload asynchronously (jnp.asarray starts the
    # transfer; nothing blocks on it) so the ~8 MiB square streams while
    # the index tensors upload and the program dispatches (VERDICT r3 #6)
    masked_dev = jnp.asarray(masked)
    t1 = _t.time()
    # codec resolved HERE (not inside the lru_cached builder) so a codec
    # switch can never serve a stale cached program
    from celestia_tpu.utils import devprof

    fn = _repair_verify_fn(k, P, chunk, with_roots, gf256.active_codec())
    fn_args = (
        masked_dev, jnp.asarray(avail),
        jnp.asarray(rk), jnp.asarray(rm),
        jnp.asarray(ck), jnp.asarray(cm),
    )
    d = devprof.dispatch("rs_repair_verify", k=k, phases=P)
    out = fn(*fn_args)
    d.done(out)
    repaired_dev, mismatch_dev, provided_mismatch_dev, roots_dev = out
    # celint: allow(host-sync) — t2 is the compute/fetch timing boundary of the repair breakdown; d.done() above only drains when profiling is armed, this sync must hold either way
    jax.block_until_ready(repaired_dev)
    t2 = _t.time()
    # ONE batched fetch of every verdict: per-array np.asarray pays a
    # full round trip each; device_get dispatches them together
    fetched = jax.device_get(
        (mismatch_dev, provided_mismatch_dev)
        + ((roots_dev,) if with_roots else ())
    )
    mismatch_axes, provided_mismatch = fetched[0], fetched[1]
    roots = fetched[2] if with_roots else None
    t3 = _t.time()
    # cost accounting after the LAST timestamp: the one-time AOT
    # compile must not be misattributed to upload/compute/fetch
    devprof.note_compile("rs_repair_verify", fn, fn_args)
    if breakdown is not None:
        breakdown.update(
            schedule_ms=(t1 - t0) * 1000.0,  # overlapped with the upload
            upload_compute_ms=(t2 - t1) * 1000.0,
            verdict_fetch_ms=(t3 - t2) * 1000.0,
            upload_overlapped=True,
        )
    if mismatch_axes.any():
        bad = np.nonzero(mismatch_axes)
        raise ByzantineError(
            f"inconsistent erasure coding at cells {list(zip(*bad))[:8]}"
        )
    if provided_mismatch.any():
        bad = np.nonzero(provided_mismatch)
        raise ByzantineError(
            f"provided shares disagree with the reconstructed codeword at "
            f"cells {list(zip(*bad))[:8]}"
        )
    if with_roots:
        for name, axis_roots, got in (
            ("row", row_roots, roots[0]),
            ("col", col_roots, roots[1]),
        ):
            if axis_roots is None:
                continue
            axis_roots = np.asarray(axis_roots, dtype=np.uint8)
            if axis_roots.shape != got.shape:
                raise ValueError(
                    f"{name}_roots must be {got.shape}, got {axis_roots.shape}"
                )
            bad = np.nonzero((axis_roots != got).any(axis=1))[0]
            if len(bad):
                raise ByzantineError(
                    f"reconstructed {name} axes {bad.tolist()[:8]} do not "
                    f"match the committed NMT roots"
                )
    if return_device:
        # all verification already ran on device; the caller keeps the
        # square in device memory (no bulk fetch)
        return repaired_dev
    t5 = _t.time()
    repaired = np.asarray(repaired_dev)
    if breakdown is not None:
        breakdown["bulk_fetch_ms"] = (_t.time() - t5) * 1000.0
    return repaired


# ---------------------------------------------------------------------------
# Repair (rsmt2d.Repair parity): iterative row/column reconstruction
# ---------------------------------------------------------------------------


def _gf_matmul_axes_host(
    D: np.ndarray, X: np.ndarray, nthreads=None
) -> np.ndarray:
    """out[i] = D[i] x X[i] over GF(256): threaded native C++ when
    available (sharded across the host pool), vectorized numpy log-table
    fallback otherwise."""
    from celestia_tpu.utils import native

    if native.available():
        return native.gf_matmul_axes(D, X, nthreads=nthreads)
    exp, log = gf256.field_tables()  # active codec's representation
    n, R, k = D.shape
    B = X.shape[2]
    out = np.zeros((n, R, B), dtype=np.uint8)
    logX = log[X.astype(np.int32)]  # [n, k, B]
    for i in range(n):
        acc = out[i]
        for j in range(k):
            col = D[i, :, j]
            nz = col != 0
            if not nz.any():
                continue
            prod = exp[
                (log[col[nz].astype(np.int32)][:, None] + logX[i, j][None, :])
                % 255
            ].astype(np.uint8)
            prod[:, X[i, j] == 0] = 0
            acc[nz] ^= prod
    return out


class ByzantineError(ValueError):
    """The available shares are not a consistent Reed-Solomon codeword
    (rsmt2d ErrByzantine parity): a malicious proposer published shares that
    disagree with the polynomial through the rest of their row/column."""


def repair_square(
    eds: np.ndarray,
    available: np.ndarray,
    row_roots: np.ndarray = None,
    col_roots: np.ndarray = None,
    nthreads: int = None,
) -> np.ndarray:
    """Reconstruct a full EDS from a partial one (rsmt2d.Repair parity).

    eds: uint8[2k, 2k, B] with garbage in unavailable cells;
    available: bool[2k, 2k] marking cells present;
    row_roots / col_roots: optional uint8[2k, 90] committed NMT axis roots
    from the block's DAH.  When given, every axis of the reconstructed
    square is re-hashed and checked against its commitment — without this,
    a malicious provider supplying k internally-consistent but *wrong*
    shares per axis would yield a "successful" reconstruction that does not
    match the block (rsmt2d.Repair verifies rebuilt axes against the
    committed roots for exactly this reason).

    Iteratively solves every row/column with >= k available cells, batching
    axes that share an availability mask into one device matmul, until the
    square is complete.  Raises ValueError if reconstruction stalls
    (insufficient data — fewer than k cells in every incomplete axis), and
    :class:`ByzantineError` if the provided shares are not a consistent
    codeword: after completion the square is re-extended from Q0 and every
    originally-available cell must match what was provided (this also
    catches inconsistent fully-available axes that need no solving), then
    checked against the committed roots when supplied.

    ``nthreads`` (None = the process pool size, ``--cpu-threads``) fans
    the per-phase decode, the re-extension and the NMT root verification
    out over the host worker pool: within a phase every solvable axis is
    independent, so the decode batch, the verify extension and the 4k
    root trees all shard cleanly.  Threaded and single-threaded repairs
    are byte-identical (tests/test_leopard_codec.py).
    """
    from celestia_tpu.utils import native as _nat

    # LAZY snapshot of the provided shares: the leopard decoder only
    # ever writes ERASED cells, so provided bytes survive in eds and the
    # final eds == recomputed check subsumes the provided-share check.
    # Only the generic matrix path overwrites whole axes (recomputed
    # bytes over provided ones) — it snapshots before its first write.
    # Skipping the eager copy saves a full square memcpy per repair.
    original_eds: np.ndarray = None
    eds = np.array(eds, dtype=np.uint8, copy=True)
    avail = np.array(available, dtype=bool, copy=True)
    n2 = eds.shape[0]
    k = n2 // 2
    if eds.shape[:2] != (n2, n2) or avail.shape != (n2, n2):
        raise ValueError("eds must be (2k, 2k, B) with matching availability mask")
    # Zero out unavailable cells so "garbage" can't leak through masks.
    eds[~avail] = 0

    while not avail.all():
        progress = False
        for axis in (0, 1):  # rows then columns
            data = eds if axis == 0 else eds.transpose(1, 0, 2)
            mask = avail if axis == 0 else avail.T
            counts = mask.sum(axis=1)
            solvable = np.nonzero((counts >= k) & (counts < n2))[0]
            if len(solvable) == 0:
                continue
            # Decode ALL solvable axes in one batched host call: under a
            # random DAS withholding pattern every axis carries a distinct
            # availability mask, so per-mask grouping degenerates to one
            # dispatch per axis — hundreds of device round-trips.
            idxs = solvable
            if (
                gf256.active_codec() == gf256.CODEC_LEOPARD
                and _nat.available()
            ):
                # leopard codec: the O(n log n) FFT erasure decode
                # (native leo_decode_axes, Forney over the novel basis)
                # — ~0.3 ms/axis at k=128 vs several ms for the
                # matrix path; bit-identical (tests/test_leopard_codec)
                if axis == 0 and bool((counts >= k).all()):
                    # fast host path (the common honest-DAS shape: every
                    # row has >= k cells): decode IN PLACE on the whole
                    # contiguous square — rows ARE the axes, complete
                    # rows are no-ops inside the decoder — skipping the
                    # ~2x33 MiB gather/scatter the index path pays
                    ok = _nat.leo_decode_axes(
                        eds, avail.astype(np.uint8), nthreads=nthreads
                    )
                    if not ok.all():
                        raise RuntimeError(
                            "leo_decode_axes rejected a solvable axis"
                        )
                    avail[:, :] = True
                    progress = True
                    continue
                block = np.ascontiguousarray(data[idxs])
                ok = _nat.leo_decode_axes(
                    block, mask[idxs].astype(np.uint8), nthreads=nthreads
                )
                if not ok.all():  # solvable==True guarantees >= k rows
                    raise RuntimeError("leo_decode_axes rejected a solvable axis")
                decoded = block
            else:
                # generic path: one Lagrange decode matrix per axis
                # (vectorized) + one threaded native GF matmul.  This
                # path overwrites whole axes, so snapshot the provided
                # bytes first (still intact in eds at this point)
                if original_eds is None:
                    original_eds = eds.copy()
                order = np.argsort(~mask[idxs], axis=1, kind="stable")
                known_idx = np.sort(order[:, :k], axis=1)  # [n_axes, k]
                D = gf256.decode_matrices_batch(known_idx.astype(np.uint8), k)
                X = np.take_along_axis(
                    data[idxs], known_idx[:, :, None], axis=1
                )  # [n_axes, k, B]
                decoded = _gf_matmul_axes_host(D, X, nthreads)  # [n_axes, 2k, B]
            if axis == 0:
                eds[idxs] = decoded
                avail[idxs] = True
            else:
                eds[:, idxs] = decoded.transpose(1, 0, 2)
                avail[:, idxs] = True
            progress = True
        if not progress:
            raise ValueError(
                "repair stalled: insufficient available cells to reconstruct"
            )

    # Byzantine check: the completed square must be the unique codeword
    # extending its Q0, and every share the caller actually provided must
    # agree with it.  (rsmt2d returns ErrByzantine from Repair here.)
    # When no generic pass ran, provided bytes are still in place in eds
    # (the leopard decoder never touches received cells), so the
    # provided-share check below is subsumed by eds == recomputed.
    orig_avail = np.asarray(available, dtype=bool)
    provided = (
        np.array(original_eds, dtype=np.uint8, copy=False)
        if original_eds is not None
        else eds
    )
    # Repair is a DAS/light-client operation: verify on the host (threaded
    # native pipeline, bit-identical to the device kernels) so repairing a
    # square never requires an accelerator or pays a cold device compile;
    # the device path remains the fallback where the native lib is absent.
    _native = _nat

    use_native = _native.available()
    use_leo = use_native and gf256.active_codec() == gf256.CODEC_LEOPARD
    need_roots = row_roots is not None or col_roots is not None
    native_roots = None
    if use_native and need_roots:
        # one threaded pass computes both the re-extension and the axis
        # roots needed for the commitment check below; the leopard codec
        # takes the O(n log n) FFT extension (same bytes, ~60x less GF
        # work than the table method at k=128)
        if use_leo:
            recomputed, native_roots, _ = _native.extend_block_leopard_cpu(
                eds[:k, :k], nthreads=nthreads
            )
        else:
            recomputed, native_roots, _ = _native.extend_block_cpu(
                eds[:k, :k], nthreads=nthreads
            )
    elif use_leo:
        recomputed = _native.leo_extend_square(eds[:k, :k], nthreads=nthreads)
    elif use_native:
        recomputed = _native.rs_extend_square(eds[:k, :k])
    else:
        recomputed = np.asarray(extend_square(eds[:k, :k]))
    if not np.array_equal(eds, recomputed):
        bad = np.nonzero((eds != recomputed).any(axis=2))
        raise ByzantineError(
            f"inconsistent erasure coding at cells {list(zip(*bad))[:8]}"
        )
    mismatch = orig_avail & (provided != recomputed).any(axis=2)
    if mismatch.any():
        bad = np.nonzero(mismatch)
        raise ByzantineError(
            f"provided shares disagree with the reconstructed codeword at "
            f"cells {list(zip(*bad))[:8]}"
        )
    if row_roots is not None or col_roots is not None:
        if native_roots is not None:
            # eds == recomputed at this point, so the pipeline's roots ARE
            # the repaired square's roots
            roots = native_roots.reshape(2, n2, 90)
        else:
            from celestia_tpu.ops import nmt as nmt_ops

            # pooled host reduction (numpy fallback when native is absent)
            roots = nmt_ops.eds_nmt_roots_host(eds, nthreads=nthreads)
        for name, axis_roots, got in (
            ("row", row_roots, roots[0]),
            ("col", col_roots, roots[1]),
        ):
            if axis_roots is None:
                continue
            axis_roots = np.asarray(axis_roots, dtype=np.uint8)
            if axis_roots.shape != got.shape:
                raise ValueError(
                    f"{name}_roots must be {got.shape}, got {axis_roots.shape}"
                )
            bad = np.nonzero((axis_roots != got).any(axis=1))[0]
            if len(bad):
                raise ByzantineError(
                    f"reconstructed {name} axes {bad.tolist()[:8]} do not "
                    f"match the committed NMT roots"
                )
    return eds


# ---------------------------------------------------------------------------
# Host reference (numpy) for bit-exactness tests
# ---------------------------------------------------------------------------


def extend_square_ref(square: np.ndarray) -> np.ndarray:
    """Pure-numpy reference of extend_square; the device must match exactly."""
    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    B = square.shape[2]
    out = np.zeros((2 * k, 2 * k, B), dtype=np.uint8)
    out[:k, :k] = square
    for r in range(k):  # row parity
        out[r, k:] = gf256.encode_shares_ref(square[r])
    for c in range(2 * k):  # column parity (over the top half)
        out[k:, c] = gf256.encode_shares_ref(out[:k, c])
    return out
