"""Device-side 2D Reed-Solomon extension and repair (JAX, MXU matmuls).

TPU-native equivalent of ``rsmt2d.ComputeExtendedDataSquare`` /
``rsmt2d.Repair`` as invoked by the reference at
/root/reference/pkg/da/data_availability_header.go:65-75 (encode) and its DAS
reconstruction surface (SURVEY.md §2.2).  Everything is integer arithmetic —
bit-exact across TPU/CPU backends and compiler versions, which is a consensus
-safety requirement (SURVEY.md §2.3 "determinism").

Representation: a square is ``uint8[k, k, 512]`` (row, column, byte).  GF(256)
linear maps are lifted to GF(2) bit-matrices (ops/gf256.py): shares are
unpacked to bit-planes, multiplied with an int8 0/1 matrix on the MXU with
int32 accumulation, reduced mod 2, and packed back to bytes.  The extension
is three batched matmuls (row parity, column parity, diagonal parity) fused
under one ``jit``.

Quadrant layout of the extended square (2k x 2k):

    Q0 | Q1        Q0 = original, Q1 = row parity,
    -------        Q2 = column parity, Q3 = parity of parity
    Q2 | Q3        (row- and column-extension commute; tested)
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from celestia_tpu.appconsts import SHARE_SIZE, is_power_of_two
from celestia_tpu.ops import gf256


def unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n, B] -> int8 bits[..., 8n, B]; bit row j*8+t = bit t of byte row j."""
    t = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> t[None, :, None]) & 1  # (..., n, 8, B)
    shape = x.shape[:-2] + (8 * x.shape[-2], x.shape[-1])
    return bits.reshape(shape).astype(jnp.int8)


def pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """int bits[..., 8n, B] -> uint8[..., n, B] (inverse of unpack_bits)."""
    shape = bits.shape[:-2] + (bits.shape[-2] // 8, 8, bits.shape[-1])
    b = bits.reshape(shape).astype(jnp.int32)
    t = jnp.arange(8, dtype=jnp.int32)
    return (b << t[None, :, None]).sum(axis=-2).astype(jnp.uint8)


def matmul_gf2(G: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    """(G @ bits) mod 2 with int32 MXU accumulation; operands int8 0/1."""
    acc = jnp.matmul(G, bits, preferred_element_type=jnp.int32)
    return (acc & 1).astype(jnp.int8)


def _row_parity(square: jnp.ndarray, G: jnp.ndarray) -> jnp.ndarray:
    """(r, k, B) uint8 -> (r, k, B) uint8 parity of each row."""
    bits = unpack_bits(square)  # (r, 8k, B)
    return pack_bits(matmul_gf2(G, bits))


def _extend(square: jnp.ndarray, G: jnp.ndarray) -> jnp.ndarray:
    """Core extension: uint8[k, k, B] -> uint8[2k, 2k, B]."""
    q0 = square
    q1 = _row_parity(q0, G)  # row parity
    q2 = _row_parity(q0.transpose(1, 0, 2), G).transpose(1, 0, 2)  # col parity
    q3 = _row_parity(q1.transpose(1, 0, 2), G).transpose(1, 0, 2)  # parity of parity
    top = jnp.concatenate([q0, q1], axis=1)
    bottom = jnp.concatenate([q2, q3], axis=1)
    return jnp.concatenate([top, bottom], axis=0)


@lru_cache(maxsize=None)
def _extend_fn(k: int):
    G = jnp.asarray(gf256.encode_matrix_bits(k))
    return jax.jit(partial(_extend, G=G))


def extend_square(square) -> jnp.ndarray:
    """Extend an original square uint8[k, k, 512] to its EDS uint8[2k, 2k, 512]."""
    square = jnp.asarray(square, dtype=jnp.uint8)
    k = square.shape[0]
    if square.shape[1] != k or not is_power_of_two(k):
        raise ValueError(f"square must be (k, k, B) with k a power of two, got {square.shape}")
    return _extend_fn(k)(square)


@lru_cache(maxsize=None)
def _extend_batched_fn(k: int):
    G = jnp.asarray(gf256.encode_matrix_bits(k))
    return jax.jit(jax.vmap(partial(_extend, G=G)))


def extend_squares_batched(squares) -> jnp.ndarray:
    """Extend a batch uint8[n, k, k, 512] -> uint8[n, 2k, 2k, 512]."""
    squares = jnp.asarray(squares, dtype=jnp.uint8)
    k = squares.shape[1]
    if squares.ndim != 4 or squares.shape[2] != k or not is_power_of_two(k):
        raise ValueError(
            f"batch must be (n, k, k, B) with k a power of two, got {squares.shape}"
        )
    return _extend_batched_fn(k)(squares)


# ---------------------------------------------------------------------------
# Repair (rsmt2d.Repair parity): iterative row/column reconstruction
# ---------------------------------------------------------------------------


def _gf_matmul_axes_host(D: np.ndarray, X: np.ndarray) -> np.ndarray:
    """out[i] = D[i] x X[i] over GF(256): threaded native C++ when
    available, vectorized numpy log-table fallback otherwise."""
    from celestia_tpu.utils import native

    if native.available():
        return native.gf_matmul_axes(D, X)
    n, R, k = D.shape
    B = X.shape[2]
    out = np.zeros((n, R, B), dtype=np.uint8)
    logX = gf256.GF_LOG[X.astype(np.int32)]  # [n, k, B]
    for i in range(n):
        acc = out[i]
        for j in range(k):
            col = D[i, :, j]
            nz = col != 0
            if not nz.any():
                continue
            prod = gf256.GF_EXP[
                (gf256.GF_LOG[col[nz].astype(np.int32)][:, None] + logX[i, j][None, :])
                % 255
            ].astype(np.uint8)
            prod[:, X[i, j] == 0] = 0
            acc[nz] ^= prod
    return out


class ByzantineError(ValueError):
    """The available shares are not a consistent Reed-Solomon codeword
    (rsmt2d ErrByzantine parity): a malicious proposer published shares that
    disagree with the polynomial through the rest of their row/column."""


def repair_square(
    eds: np.ndarray,
    available: np.ndarray,
    row_roots: np.ndarray = None,
    col_roots: np.ndarray = None,
) -> np.ndarray:
    """Reconstruct a full EDS from a partial one (rsmt2d.Repair parity).

    eds: uint8[2k, 2k, B] with garbage in unavailable cells;
    available: bool[2k, 2k] marking cells present;
    row_roots / col_roots: optional uint8[2k, 90] committed NMT axis roots
    from the block's DAH.  When given, every axis of the reconstructed
    square is re-hashed and checked against its commitment — without this,
    a malicious provider supplying k internally-consistent but *wrong*
    shares per axis would yield a "successful" reconstruction that does not
    match the block (rsmt2d.Repair verifies rebuilt axes against the
    committed roots for exactly this reason).

    Iteratively solves every row/column with >= k available cells, batching
    axes that share an availability mask into one device matmul, until the
    square is complete.  Raises ValueError if reconstruction stalls
    (insufficient data — fewer than k cells in every incomplete axis), and
    :class:`ByzantineError` if the provided shares are not a consistent
    codeword: after completion the square is re-extended from Q0 and every
    originally-available cell must match what was provided (this also
    catches inconsistent fully-available axes that need no solving), then
    checked against the committed roots when supplied.
    """
    original_eds = np.array(eds, dtype=np.uint8, copy=True)
    eds = np.array(eds, dtype=np.uint8, copy=True)
    avail = np.array(available, dtype=bool, copy=True)
    n2 = eds.shape[0]
    k = n2 // 2
    if eds.shape[:2] != (n2, n2) or avail.shape != (n2, n2):
        raise ValueError("eds must be (2k, 2k, B) with matching availability mask")
    # Zero out unavailable cells so "garbage" can't leak through masks.
    eds[~avail] = 0

    while not avail.all():
        progress = False
        for axis in (0, 1):  # rows then columns
            data = eds if axis == 0 else eds.transpose(1, 0, 2)
            mask = avail if axis == 0 else avail.T
            counts = mask.sum(axis=1)
            solvable = np.nonzero((counts >= k) & (counts < n2))[0]
            if len(solvable) == 0:
                continue
            # Decode ALL solvable axes in one batched host call: under a
            # random DAS withholding pattern every axis carries a distinct
            # availability mask, so per-mask grouping degenerates to one
            # dispatch per axis — hundreds of device round-trips.  Instead
            # build one Lagrange decode matrix per axis (vectorized) and
            # run one threaded native GF matmul over the whole batch.
            idxs = solvable
            # first k available positions per axis: stable argsort of ~mask
            order = np.argsort(~mask[idxs], axis=1, kind="stable")
            known_idx = np.sort(order[:, :k], axis=1)  # [n_axes, k]
            D = gf256.decode_matrices_batch(known_idx.astype(np.uint8), k)
            X = np.take_along_axis(
                data[idxs], known_idx[:, :, None], axis=1
            )  # [n_axes, k, B]
            decoded = _gf_matmul_axes_host(D, X)  # [n_axes, 2k, B]
            if axis == 0:
                eds[idxs] = decoded
                avail[idxs] = True
            else:
                eds[:, idxs] = decoded.transpose(1, 0, 2)
                avail[:, idxs] = True
            progress = True
        if not progress:
            raise ValueError(
                "repair stalled: insufficient available cells to reconstruct"
            )

    # Byzantine check: the completed square must be the unique codeword
    # extending its Q0, and every share the caller actually provided must
    # agree with it.  (rsmt2d returns ErrByzantine from Repair here.)
    orig_avail = np.asarray(available, dtype=bool)
    provided = np.array(original_eds, dtype=np.uint8, copy=False)
    # Repair is a DAS/light-client operation: verify on the host (threaded
    # native pipeline, bit-identical to the device kernels) so repairing a
    # square never requires an accelerator or pays a cold device compile;
    # the device path remains the fallback where the native lib is absent.
    from celestia_tpu.utils import native as _native

    use_native = _native.available()
    need_roots = row_roots is not None or col_roots is not None
    native_roots = None
    if use_native and need_roots:
        # one threaded pass computes both the re-extension and the axis
        # roots needed for the commitment check below
        recomputed, native_roots, _ = _native.extend_block_cpu(
            eds[:k, :k], nthreads=0
        )
    elif use_native:
        recomputed = _native.rs_extend_square(eds[:k, :k])
    else:
        recomputed = np.asarray(extend_square(eds[:k, :k]))
    if not np.array_equal(eds, recomputed):
        bad = np.nonzero((eds != recomputed).any(axis=2))
        raise ByzantineError(
            f"inconsistent erasure coding at cells {list(zip(*bad))[:8]}"
        )
    mismatch = orig_avail & (provided != recomputed).any(axis=2)
    if mismatch.any():
        bad = np.nonzero(mismatch)
        raise ByzantineError(
            f"provided shares disagree with the reconstructed codeword at "
            f"cells {list(zip(*bad))[:8]}"
        )
    if row_roots is not None or col_roots is not None:
        if native_roots is not None:
            # eds == recomputed at this point, so the pipeline's roots ARE
            # the repaired square's roots
            roots = native_roots.reshape(2, n2, 90)
        else:
            from celestia_tpu.ops import nmt as nmt_ops

            roots = np.asarray(nmt_ops.eds_nmt_roots(eds))
        for name, axis_roots, got in (
            ("row", row_roots, roots[0]),
            ("col", col_roots, roots[1]),
        ):
            if axis_roots is None:
                continue
            axis_roots = np.asarray(axis_roots, dtype=np.uint8)
            if axis_roots.shape != got.shape:
                raise ValueError(
                    f"{name}_roots must be {got.shape}, got {axis_roots.shape}"
                )
            bad = np.nonzero((axis_roots != got).any(axis=1))[0]
            if len(bad):
                raise ByzantineError(
                    f"reconstructed {name} axes {bad.tolist()[:8]} do not "
                    f"match the committed NMT roots"
                )
    return eds


# ---------------------------------------------------------------------------
# Host reference (numpy) for bit-exactness tests
# ---------------------------------------------------------------------------


def extend_square_ref(square: np.ndarray) -> np.ndarray:
    """Pure-numpy reference of extend_square; the device must match exactly."""
    square = np.asarray(square, dtype=np.uint8)
    k = square.shape[0]
    B = square.shape[2]
    out = np.zeros((2 * k, 2 * k, B), dtype=np.uint8)
    out[:k, :k] = square
    for r in range(k):  # row parity
        out[r, k:] = gf256.encode_shares_ref(square[r])
    for c in range(2 * k):  # column parity (over the top half)
        out[k:, c] = gf256.encode_shares_ref(out[:k, c])
    return out
